"""Contract tests for the inference bench lanes: tools/serve_bench.py
(record shape, --ab, --require-finished) and the decode_bench
satellite fixes (shared model construction, --steps validation)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = ["--layers", "2", "--d-model", "64", "--heads", "2",
        "--vocab", "128", "--requests", "6", "--rate", "50",
        "--prompt-min", "4", "--prompt-max", "12",
        "--new-min", "2", "--new-max", "6", "--decode-slots", "2",
        "--prefill-chunk", "4", "--page-size", "8"]


def _run(script, *argv, check=True):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *argv],
        capture_output=True, text=True, env=env, timeout=600)
    if check:
        assert p.returncode == 0, p.stderr[-2000:]
    return p


class TestServeBenchContract:
    def test_continuous_record_contract(self):
        """The CI smoke lane's contract (tools/check.sh): one JSON
        line with tokens/s/chip, p50/p99 TTFT, p50/p99 per-token
        latency, page occupancy — all requests finished and the greedy
        streams pinned against lm_decode."""
        p = _run("serve_bench.py", *TINY, "--pin-exact",
                 "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_continuous_tokens_per_sec_per_chip"
        assert rec["unit"] == "tokens/sec/chip"
        assert rec["value"] > 0
        s = rec["serve"]
        assert s["by_state"] == {"finished": 6}
        for key in ("p50", "p99"):
            assert s["ttft_ms"][key] is not None
            assert s["tbt_ms"][key] is not None
        assert 0 < s["pages"]["occupancy_max"] <= 1
        assert rec["config"]["policy"] == "fcfs"

    def test_ab_record_carries_both_sides(self):
        p = _run("serve_bench.py", *TINY, "--ab")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_ab_tokens_per_sec_per_chip"
        ab = rec["serve"]["ab"]
        assert ab["static"]["tokens_per_sec_per_chip"] > 0
        assert ab["continuous_over_static"] is not None

    def test_attention_paged_record_contract(self):
        """--attention paged: same record contract, all greedy streams
        still pinned against lm_decode, plus the kernel's traffic
        accounting stamped (live-page bytes strictly below the gather
        path's)."""
        p = _run("serve_bench.py", *TINY, "--attention", "paged",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["config"]["attention"] == "paged"
        a = rec["serve"]["attention"]
        assert a["mode"] == "paged"
        assert 0 < a["kv_fetch_frac"] < 1
        assert a["kv_bytes_per_step_paged"] < \
            a["kv_bytes_per_step_gather"]

    def test_ab_attention_record_carries_both_sides(self):
        """--ab-attention: one record with the paged side as headline,
        the gather side + the paged_over_gather throughput ratio under
        serve.ab_attention, and the static byte accounting on BOTH
        sides."""
        p = _run("serve_bench.py", *TINY, "--requests", "4",
                 "--ab-attention")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == \
            "serve_ab_attention_tokens_per_sec_per_chip"
        assert rec["config"]["attention"] == "ab"
        s = rec["serve"]
        assert s["attention"]["mode"] == "paged"
        ab = s["ab_attention"]
        assert ab["gather"]["attention"]["mode"] == "gather"
        assert ab["gather"]["tokens_per_sec_per_chip"] > 0
        assert ab["paged_over_gather"] is not None
        for side in (s, ab["gather"]):
            assert 0 < side["attention"]["kv_fetch_frac"] < 1

    def test_ab_attention_is_exclusive_with_other_modes(self):
        for extra in (["--ab"], ["--static"]):
            p = _run("serve_bench.py", *TINY, "--ab-attention", *extra,
                     check=False)
            assert p.returncode == 2, (extra, p.stderr[-300:])

    def test_ab_prefix_record_contract(self):
        """--ab-prefix (round-16 acceptance, single-engine edition):
        the many-users-one-system-prompt workload runs cold THEN
        cached, the cached side must actually save prefill tokens with
        exactly one cold prefill for the shared prefix, every greedy
        stream is bit-identical off vs on AND pinned against lm_decode,
        and the record stamps both sides + the hit accounting."""
        p = _run("serve_bench.py", *TINY, "--ab-prefix",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_ab_prefix_tokens_per_sec_per_chip"
        s = rec["serve"]
        assert s["mode"] == "ab_prefix"
        assert s["by_state"] == {"finished": 6}
        pb = s["prefix"]
        assert pb["hit_rate"] > 0
        assert pb["prefill_tokens_saved"] > 0
        assert pb["cow_copies"] == 0     # decode never lands on shared
        ab = s["ab_prefix"]
        assert ab["off"]["prefix"] is None   # explicit off-side stamp
        assert ab["off"]["by_state"] == {"finished": 6}
        assert ab["system_prompt_tokens"] == 32   # auto: 4 pages
        assert ab["unique_prefixes"] == 1         # one system prompt
        assert ab["cold_prefills"] == 1           # exactly one cold
        assert ab["exact_pin"]["identical"] is True
        assert ab["exact_pin"]["compared"] == 6
        assert rec["config"]["prefix_caching"] == "ab"
        assert rec["config"]["system_prompt_len"] == 32
        # the perf_summary prefix column renders this record
        from tools.perf_summary import prefix_cell

        cell = prefix_cell(rec)
        assert cell.startswith("hit ") and "a/b" in cell

    def test_ab_prefix_is_exclusive_with_other_modes(self):
        for extra in (["--ab"], ["--static"], ["--ab-attention"],
                      ["--prefix"],
                      ["--fleet", "2", "--fault-plan",
                       "kill:replica=1,at=50%"]):
            p = _run("serve_bench.py", *TINY, "--ab-prefix", *extra,
                     check=False)
            assert p.returncode == 2, (extra, p.stderr[-300:])

    def test_ab_tp_record_contract(self):
        """--ab-tp (round-18 acceptance): the identical workload runs
        unsharded then head-sharded over dp=1,tp=4; the bench aborts
        unless every greedy stream is bit-identical and the sharded
        side's per-chip KV bytes are at most 1/tp — so a passing run
        IS the exactness+bandwidth evidence, and the record stamps
        serve.tp{degree, kv_bytes_per_chip, tp_over_single}."""
        p = _run("serve_bench.py", *TINY, "--heads", "4",
                 "--mesh", "dp=1,tp=4", "--ab-tp",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_ab_tp_tokens_per_sec_per_chip"
        s = rec["serve"]
        assert s["mode"] == "ab_tp"
        assert s["by_state"] == {"finished": 6}
        assert s["attention"]["tp"] == 4
        tp = s["tp"]
        assert tp["degree"] == 4 and tp["mesh"] == "dp=1,tp=4"
        assert tp["exact_pin"]["identical"] is True
        assert tp["exact_pin"]["compared"] == 6
        assert tp["kv_bytes_per_chip"] == pytest.approx(
            tp["kv_bytes_per_chip_single"] / 4, rel=1e-3)
        assert tp["tp_over_single"] is not None
        assert rec["config"]["mesh"] == "dp=1,tp=4"
        # the perf_summary serve column renders the tp tag
        from tools.perf_summary import serve_cell

        cell = serve_cell(rec)
        assert " tp4 kv 0.25x" in cell

    def test_ab_tp_arg_validation(self):
        # --ab-tp without a mesh, with another A/B, a mesh that
        # resolves to tp=1, and mesh+fleet are all argparse errors
        for argv in (["--ab-tp"],
                     ["--mesh", "dp=1,tp=2", "--ab-tp", "--ab"],
                     ["--mesh", "dp=1", "--ab-tp"],
                     ["--mesh", "garbage", "--ab-tp"],
                     ["--mesh", "dp=1,tp=2", "--fleet", "2"]):
            p = _run("serve_bench.py", *TINY, *argv, check=False)
            assert p.returncode == 2, (argv, p.stderr[-300:])


    def test_ab_spec_record_contract(self):
        """--ab-spec (round 19): one record, speculative side as the
        headline, the non-spec side under serve.ab_spec.base, the
        greedy streams of BOTH sides pinned bit-identical
        (exact_pin.identical), and the full-depth draft's
        deterministic accounting: accept_rate exactly 1.0,
        tokens_per_step > 1."""
        p = _run("serve_bench.py", *TINY, "--speculate", "4",
                 "--draft-layers", "2", "--ab-spec", "--pin-exact",
                 "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_ab_spec_tokens_per_sec_per_chip"
        assert rec["config"]["speculate_k"] == "ab"
        s = rec["serve"]
        assert s["mode"] == "ab_spec"
        assert s["spec"]["k"] == 4 and s["spec"]["draft_layers"] == 2
        ab = s["ab_spec"]
        assert ab["k"] == 4 and ab["draft_layers"] == 2
        assert ab["base"]["spec"] is None
        assert ab["base"]["tokens_per_sec_per_chip"] > 0
        assert ab["exact_pin"]["identical"] is True
        assert ab["exact_pin"]["compared"] == 6
        # draft depth == target depth (TINY has 2 layers): the draft
        # IS the target, so acceptance is total by construction
        assert ab["accept_rate"] == 1.0
        assert ab["tokens_per_step"] > 1.0
        assert ab["spec_over_base"] is not None

    def test_ab_spec_arg_validation(self):
        # --ab-spec without --speculate, with every other A/B mode,
        # with a fleet, plus the bare spec-knob misuses are all
        # argparse errors
        for argv in (["--ab-spec"],
                     ["--speculate", "2", "--ab-spec", "--ab"],
                     ["--speculate", "2", "--ab-spec", "--static"],
                     ["--speculate", "2", "--ab-spec",
                      "--ab-attention"],
                     ["--speculate", "2", "--ab-spec", "--ab-prefix"],
                     ["--speculate", "2", "--ab-spec", "--fleet", "2"],
                     ["--speculate", "-1"],
                     ["--draft-layers", "1"]):
            p = _run("serve_bench.py", *TINY, *argv, check=False)
            assert p.returncode == 2, (argv, p.stderr[-300:])

    def test_require_finished_fails_loudly(self):
        # capacity of ONE page (8 positions): several drawn requests
        # can never fit and hard-reject -> --require-finished exits 1
        p = _run("serve_bench.py", *TINY, "--num-pages", "2",
                 "--require-finished", check=False)
        assert p.returncode != 0
        assert "not all requests finished" in (p.stderr + p.stdout)

    def test_bad_args_are_argparse_errors(self):
        for bad in (["--rate", "0"], ["--requests", "0"],
                    ["--prompt-min", "9", "--prompt-max", "4"]):
            p = _run("serve_bench.py", *TINY[:-2], *bad, check=False)
            assert p.returncode == 2, (bad, p.stderr[-300:])


class TestFleetBenchContract:
    def test_fleet_fault_ab_record_contract(self):
        """The round-12 acceptance e2e: --fleet 2 with a mid-run
        replica kill runs clean THEN faulted on the identical workload,
        pins every both-finished greedy stream bit-identical, classes
        the incident, and stamps the recovery metrics."""
        p = _run("serve_bench.py", *TINY, "--rate", "200",
                 "--fleet", "2", "--fault-plan", "kill:replica=1,at=50%",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == \
            "serve_fleet_fault_ab_tokens_per_sec_per_chip"
        s = rec["serve"]
        assert s["mode"] == "fleet_fault_ab"
        assert s["by_state"] == {"finished": 6}
        f = s["fleet"]
        assert f["incidents_by_class"] == {"crashed": 1}
        assert f["replicas"] == 2
        # never FAILED (budget 2); whether the relaunch landed before
        # the fleet drained is timing, so only pin the invariant
        assert f["failed"] == 0
        inc = f["incidents"][0]
        assert inc["category"] == "crashed" and inc["code"] == -9
        ab = s["fleet_ab"]
        assert ab["redispatch_pin"]["identical"] is True
        assert ab["redispatch_pin"]["compared"] == 6
        assert ab["clean"]["by_state"] == {"finished": 6}
        assert ab["p99_ttft_clean_ms"] is not None
        assert ab["p99_ttft_faulted_ms"] is not None
        assert rec["config"]["fleet"]["replicas"] == 2
        assert rec["config"]["fleet"]["fault_plan"] == \
            "kill:replica=1,at=50%"
        # the perf_summary fleet column renders this record
        from tools.perf_summary import fleet_cell

        cell = fleet_cell(rec)
        assert cell.startswith("2r") and "crashed1" in cell

    def test_fleet_process_transport_record_contract(self):
        """The round-13 acceptance e2e: the same fault A/B with one
        worker OS process per replica — the kill SIGKILLs a REAL
        process (incident code -9 from the reaped exit), the record
        stamps transport='process' + per-RPC overhead + transport
        incident counts, and no worker process survives the bench."""
        def worker_pids():
            ps = subprocess.run(
                ["pgrep", "-f", "horovod_tpu.serve.worker"],
                capture_output=True, text=True)
            return set(ps.stdout.split())

        pre = worker_pids()   # other jobs' workers are not ours to judge
        p = _run("serve_bench.py", *TINY, "--rate", "200",
                 "--fleet", "2", "--fleet-transport", "process",
                 "--fault-plan", "kill:replica=1,at=50%",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        s = rec["serve"]
        assert s["mode"] == "fleet_fault_ab"
        assert s["by_state"] == {"finished": 6}
        f = s["fleet"]
        assert f["transport"] == "process"
        assert f["rpc_ms"]["calls"] > 0
        assert f["rpc_ms"]["p50"] is not None
        assert f["rpc_ms"]["p99"] is not None
        assert f["incidents_by_class"] == {"crashed": 1}
        inc = f["incidents"][0]
        assert inc["category"] == "crashed" and inc["code"] == -9
        ab = s["fleet_ab"]
        assert ab["redispatch_pin"]["identical"] is True
        assert ab["redispatch_pin"]["compared"] == 6
        # both A/B sides stamp the transport evidence
        assert ab["clean"]["fleet"]["transport"] == "process"
        assert ab["clean"]["fleet"]["rpc_ms"]["calls"] > 0
        assert rec["config"]["fleet"]["transport"] == "process"
        from tools.perf_summary import fleet_cell

        cell = fleet_cell(rec)
        assert "proc" in cell and "rpc" in cell
        # no zombie/orphan workers survive the bench process (scoped:
        # only NEW pids count — a concurrent job's workers are not
        # this bench's leak)
        leaked = worker_pids() - pre
        assert not leaked, leaked

    def test_fleet_clean_record_contract(self):
        p = _run("serve_bench.py", *TINY, "--fleet", "2",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_fleet_tokens_per_sec_per_chip"
        s = rec["serve"]
        assert s["mode"] == "fleet"
        assert s["by_state"] == {"finished": 6}
        f = s["fleet"]
        assert f["incidents"] == [] and f["redispatched"] == 0
        assert f["healthy"] == 2
        assert "fleet_ab" not in s

    def test_fleet_ab_prefix_record_contract(self):
        """--fleet 2 --ab-prefix: the cold pin tightens to one cold
        prefill per (prefix, REPLICA) — rendezvous routing sends every
        prefix-mate to one home unless saturation spills, and each
        replica that serves the prefix pays for it exactly once."""
        p = _run("serve_bench.py", *TINY, "--fleet", "2", "--ab-prefix",
                 "--pin-exact", "--require-finished")
        rec = json.loads(p.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "serve_ab_prefix_tokens_per_sec_per_chip"
        s = rec["serve"]
        assert s["mode"] == "ab_prefix"
        assert s["by_state"] == {"finished": 6}
        pb = s["fleet"]["prefix"]
        assert pb["hits"] > 0 and pb["prefill_tokens_saved"] > 0
        ab = s["ab_prefix"]
        assert ab["off"]["fleet"]["prefix"] is None
        assert ab["unique_prefixes"] == 1
        # one cold prefill per replica the prefix landed on, no more
        assert ab["cold_prefills"] == ab["replica_homes"] >= 1
        assert ab["exact_pin"]["identical"] is True
        assert ab["exact_pin"]["compared"] == 6
        from tools.perf_summary import prefix_cell

        assert prefix_cell(rec).startswith("hit ")

    def test_fleet_arg_validation(self):
        cases = [
            # faults address replicas: need --fleet
            ["--fault-plan", "kill:replica=0,at=1s"],
            # replica outside the fleet
            ["--fleet", "2", "--fault-plan", "kill:replica=5,at=1s"],
            # malformed plan dies in argparse, not mid-run
            ["--fleet", "2", "--fault-plan", "explode:replica=0,at=1s"],
            # a stall with no watchdog would hang the lane forever
            ["--fleet", "2", "--fault-plan", "stall:replica=0,at=1s"],
            # one A/B per record
            ["--fleet", "2", "--ab"],
            ["--fleet", "2", "--ab-attention"],
            ["--fleet", "2", "--static"],
        ]
        for bad in cases:
            p = _run("serve_bench.py", *TINY, *bad, check=False)
            assert p.returncode == 2, (bad, p.stderr[-300:])


def test_fleet_cell_renders_synthetic_record():
    """tools/perf_summary.py fleet column (fast, no subprocess)."""
    from tools.perf_summary import fleet_cell

    assert fleet_cell({}) == "—"
    assert fleet_cell({"serve": {"ttft_ms": {}}}) == "—"
    rec = {"serve": {
        "fleet": {"replicas": 2,
                  "incidents_by_class": {"crashed": 1, "stalled": 2},
                  "redispatched": 3, "tokens_recomputed": 10,
                  "detect_s": 0.8, "shed": 2},
        "fleet_ab": {"faulted_over_clean_p99_ttft": 2.07},
    }}
    cell = fleet_cell(rec)
    assert cell == "2r crashed1,stalled2 rd3/10tok det 0.8s shed2 f/c 2.07"
    # process-transport records grow the proc tag + rpc overhead pair;
    # inproc records tag without rpc; pre-transport records (above)
    # stay untagged.
    proc = {"serve": {"fleet": {
        "replicas": 2, "transport": "process",
        "rpc_ms": {"calls": 10, "p50": 0.3, "p99": 2.1},
        "incidents_by_class": {"crashed": 1}, "redispatched": 1,
        "tokens_recomputed": 4}}}
    assert fleet_cell(proc) == "2r proc rpc 0.3/2.1ms crashed1 rd1/4tok"
    inp = {"serve": {"fleet": {"replicas": 2, "transport": "inproc"}}}
    assert fleet_cell(inp) == "2r inproc"
    # tcp records tag the transport + host count; host_down incidents
    # ride the incidents_by_class render like any other class.
    tcp = {"serve": {"fleet": {
        "replicas": 2, "transport": "tcp", "hosts": 2,
        "rpc_ms": {"calls": 10, "p50": 0.4, "p99": 3.0},
        "incidents_by_class": {"host_down": 1}, "redispatched": 4,
        "tokens_recomputed": 18}}}
    assert fleet_cell(tcp) == \
        "2r tcp 2h rpc 0.4/3ms host_down1 rd4/18tok"


def test_prefix_cell_renders_synthetic_record():
    """tools/perf_summary.py prefix column (fast, no subprocess)."""
    from tools.perf_summary import prefix_cell

    assert prefix_cell({}) == "—"
    assert prefix_cell({"serve": {"ttft_ms": {}}}) == "—"
    assert prefix_cell({"serve": {"prefix": None}}) == "—"
    # single-engine --ab-prefix record: hit accounting + A/B ratio
    eng = {"serve": {
        "prefix": {"hit_rate": 0.88, "prefill_tokens_saved": 224,
                   "pages_shared": 14, "cow_copies": 0},
        "ab_prefix": {"cached_over_cold": 1.05, "cold_prefills": 1,
                      "unique_prefixes": 1},
    }}
    assert prefix_cell(eng) == "hit 0.88 sv 224tok/14pg a/b 1.05 1cold x1"
    # fleet records read the router-side block and append the
    # redispatch-meets-prefix savings
    fl = {"serve": {"fleet": {"prefix": {
        "hit_rate": 0.75, "prefill_tokens_saved": 48,
        "pages_shared": 6, "redispatch_tokens_saved": 16}}}}
    assert prefix_cell(fl) == "hit 0.75 sv 48tok/6pg rd16tok"
    # COW copies surface when the defensive path ever fired
    cow = {"serve": {"prefix": {"hit_rate": 0.5,
                                "prefill_tokens_saved": 8,
                                "cow_copies": 2}}}
    assert prefix_cell(cow) == "hit 0.5 sv 8tok cow2"


class TestDecodeBenchSatellites:
    def test_steps_zero_is_an_argparse_error(self):
        """The satellite fix: --steps 0 must die in argparse, not as a
        downstream scan/shape failure."""
        p = _run("decode_bench.py", "--steps", "0", check=False)
        assert p.returncode == 2
        assert "--steps must be >= 1" in p.stderr

    def test_negative_iters_rejected(self):
        p = _run("decode_bench.py", "--iters", "0", check=False)
        assert p.returncode == 2

    def test_shared_builder_shapes(self):
        """decode_bench and serve_bench build the SAME model through
        tools.lm_common (the A/B precondition)."""
        import argparse

        from tools.lm_common import (add_model_args, build_params,
                                     validate_model_args)

        ap = argparse.ArgumentParser()
        add_model_args(ap)
        args = ap.parse_args(["--layers", "2", "--d-model", "64",
                              "--heads", "2", "--vocab", "128"])
        validate_model_args(ap, args)
        params = build_params(args, max_len=32)
        assert len(params["layers"]) == 2
        assert params["embed"].shape == (128, 64)
        assert params["pos"].shape == (32, 64)
        assert params["layers"][0]["wqkv"].shape == (64, 3, 2, 32)
        assert params["layers"][0]["wup"].shape == (64, 256)

    def test_d_model_heads_divisibility_error(self):
        p = _run("decode_bench.py", "--d-model", "100", "--heads", "12",
                 check=False)
        assert p.returncode == 2
        assert "divisible" in p.stderr
