"""tools/scaling_model.py: the measured bucket-byte accounting and the
scaling-efficiency model built on it (VERDICT r5 ask #2: "assert the
bucket-plan numbers in a test").

The per-model pins are the EXACT plans `fused_reduce` executes at the
default 64 MiB HOROVOD_FUSION_THRESHOLD over each benchmark model's
parameter tree (via jax.eval_shape — zero param FLOPs): if a model zoo
or fusion-planner change moves these numbers, the published prediction
table in docs/benchmarks.md is stale and must be regenerated.
"""

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.scaling_model import (  # noqa: E402
    CHIP_LADDER,
    DEFAULT_DISPATCH_US,
    MEASURED,
    bucket_stats,
    efficiency_table,
    predict_efficiency,
    ring_allreduce_us,
    step_time_ms,
)
from horovod_tpu.common.config import DEFAULT_FUSION_THRESHOLD  # noqa: E402

# (buckets, total MB, oversize singletons) at the default 64 MiB
# threshold — the numbers docs/benchmarks.md's prediction table cites.
# ResNet-50: 97.49 MB of fp32 grads in 2 buckets; VGG-16's fc1 kernel
# (25088x4096 = 392 MB) is an oversize singleton; the LM lanes' embed /
# lm_head tables (vocab 32000) are the two oversize singletons there.
EXPECTED_PLANS = {
    "resnet50": (2, 97.49, 0),
    "vgg16": (5, 527.81, 1),
    "transformer_lm": (8, 517.86, 2),
    "transformer_lm_medium": (26, 1410.95, 2),
}


@pytest.mark.parametrize("model", sorted(EXPECTED_PLANS))
def test_bucket_plan_numbers(model):
    plan, summary = bucket_stats(model, DEFAULT_FUSION_THRESHOLD)
    count, total_mb, oversize = EXPECTED_PLANS[model]
    assert summary["count"] == count, summary
    assert summary["total_mb"] == total_mb, summary
    assert summary["oversize_singletons"] == oversize, summary
    # Internal consistency: the plan IS the summary's evidence.
    assert len(plan) == count
    assert sum(b.nbytes for b in plan) == summary["total_bytes"]
    assert sum(1 for b in plan if b.oversize) == oversize
    # Every tensor lands in exactly one bucket.
    members = [i for b in plan for i in b.members]
    assert sorted(members) == list(range(len(members)))


def test_plans_cover_every_modeled_lane():
    assert set(EXPECTED_PLANS) == set(MEASURED)


def test_ring_time_shape():
    # n=1: no collective. Monotone in n (latency terms) and in bytes.
    assert ring_allreduce_us(10**6, 1, 200.0, 1.0, 5.0) == 0.0
    t8 = ring_allreduce_us(10**6, 8, 200.0, 1.0, 5.0)
    t64 = ring_allreduce_us(10**6, 64, 200.0, 1.0, 5.0)
    assert 0 < t8 < t64
    assert ring_allreduce_us(2 * 10**6, 8, 200.0, 1.0, 5.0) > t8


@pytest.mark.parametrize("model", sorted(EXPECTED_PLANS))
def test_efficiency_monotone_and_bounded(model):
    stats = bucket_stats(model, DEFAULT_FUSION_THRESHOLD)
    prev = None
    for n in CHIP_LADDER:
        p = predict_efficiency(model, n, DEFAULT_FUSION_THRESHOLD,
                               overlap="off", _stats=stats)
        assert 0 < p["efficiency"] <= 1.0
        if prev is not None:
            assert p["efficiency"] <= prev + 1e-12
        prev = p["efficiency"]


@pytest.mark.parametrize("dcn_inner", [0, 8])
def test_overlap_never_hurts_predicted_efficiency(dcn_inner):
    for model in EXPECTED_PLANS:
        stats = bucket_stats(model, DEFAULT_FUSION_THRESHOLD)
        for n in (8, 64):
            off = predict_efficiency(model, n, DEFAULT_FUSION_THRESHOLD,
                                     overlap="off", dcn_inner=dcn_inner,
                                     _stats=stats)
            on = predict_efficiency(model, n, DEFAULT_FUSION_THRESHOLD,
                                    overlap="auto", dcn_inner=dcn_inner,
                                    _stats=stats)
            assert on["efficiency"] >= off["efficiency"] - 1e-9
            assert on["exposed_ms"] <= off["comm_ms"] + 1e-9


def test_tiny_threshold_pays_latency():
    """The fusion threshold is a real knob in the model: shattering
    ResNet-50 into per-KB buckets must cost predicted efficiency at
    scale (per-bucket latency + dispatch), which is the whole argument
    for fusion."""
    n = 64
    fused = predict_efficiency("resnet50", n, DEFAULT_FUSION_THRESHOLD,
                               overlap="off")
    shattered = predict_efficiency("resnet50", n, 64 * 1024, overlap="off")
    assert shattered["buckets"] > 10 * fused["buckets"]
    assert shattered["efficiency"] < fused["efficiency"]


def test_step_time_sources():
    # Measured rows carry the honest round-5 numbers; the estimated
    # medium lane derives from its own bucket bytes and says so.
    _, summary = bucket_stats("transformer_lm_medium",
                              DEFAULT_FUSION_THRESHOLD)
    est = step_time_ms("transformer_lm_medium", summary)
    assert 50 < est < 5000
    assert MEASURED["transformer_lm_medium"]["step_ms"] is None
    assert abs(step_time_ms("resnet50", None) - 64 / 1906 * 1e3) < 1e-9


def test_efficiency_table_renders_markdown():
    table = efficiency_table(DEFAULT_FUSION_THRESHOLD, overlap="auto",
                             dispatch_us=DEFAULT_DISPATCH_US,
                             models=["resnet50"])
    lines = table.splitlines()
    assert lines[0].startswith("| model | buckets | grad MB | step ms |")
    assert len(lines) == 3
    assert "resnet50" in lines[2] and "%" in lines[2]


def test_efficiency_table_mesh_chip_restriction():
    """--mesh restricts the ladder to the config's device product: one
    prediction column at exactly that chip count, not the full sweep."""
    table = efficiency_table(DEFAULT_FUSION_THRESHOLD,
                             models=["resnet50"], chips=[32])
    header = table.splitlines()[0]
    assert header.endswith("| 32c |")
    assert header.count("c |") == 1
