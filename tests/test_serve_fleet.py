"""Fault-tolerant serving fleet (horovod_tpu/serve/fleet.py + router.py).

The acceptance pins:

* a replica KILLED mid-decode has its in-flight requests drained and
  redispatched to survivors, and every greedy stream stays
  BIT-IDENTICAL to the fault-free run (at-most-once: emitted tokens are
  never re-emitted — the generated-so-far prefix rides back as prompt
  through the eviction-recompute arithmetic);
* a silent STALL becomes a classified incident: heartbeat goes stale,
  the (real, PR-9) HealthWatchdog kills the replica, the incident
  classes ``stalled`` via the WorkerExit taxonomy, and the fleet
  finishes everything after the budgeted relaunch (slow-marked: real
  wall clock);
* load shedding tells the truth: the bounded router queue rejects
  overflow terminally as ``overloaded`` with a retry-after hint,
  infeasible requests as ``infeasible``, and REJECTED requests never
  allocate a single KV page (allocator conservation).

Everything except the watchdog lane runs on an injectable fake clock.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.elastic.faults import (FaultPlanError, ServeFaultAction,
                                        parse_serve_fault_plan)
from horovod_tpu.models import parallel_lm as plm
from horovod_tpu.serve import (FleetConfig, Request, ServeConfig,
                               ServeFleet)
from horovod_tpu.serve.router import (eligible, pick_replica,
                                      retry_after_hint)
from horovod_tpu.serve.scheduler import rebase_for_recompute

V, LMAX, LAYERS, H, DH, FFN = 64, 64, 2, 2, 8, 32


@pytest.fixture(scope="module")
def params():
    return plm.init_lm_params(jax.random.PRNGKey(0), V, LMAX, LAYERS, H,
                              DH, FFN)


def _prompt(i, lp):
    key = jax.random.fold_in(jax.random.PRNGKey(100), i)
    return np.asarray(jax.random.randint(key, (lp,), 0, V), np.int32)


def _ref(params, prompt, steps):
    return list(np.asarray(
        plm.lm_decode(params, jnp.asarray(prompt)[None], steps))[0])


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def _cfg(**kw):
    base = dict(page_size=8, num_pages=32, decode_slots=2,
                prefill_chunk=4)
    base.update(kw)
    return ServeConfig(**base)


def _fleet(params, clk=None, cfg=None, **fleet_kw):
    fleet_kw.setdefault("replicas", 2)
    fleet_kw.setdefault("backoff_base", 0.01)
    kw = {}
    if clk is not None:
        kw = {"clock": clk, "sleep": clk.sleep}
    return ServeFleet(params, cfg or _cfg(), FleetConfig(**fleet_kw),
                      **kw)


# ------------------------------------------------------ fault grammar


class TestServeFaultGrammar:
    def test_parses_the_issue_example(self):
        acts = parse_serve_fault_plan(
            "kill:replica=1,at=2.5s; stall:replica=0,at=4s; "
            "slow:replica=2,at=1s,factor=3")
        assert [a.kind for a in acts] == ["kill", "stall", "slow"]
        assert [a.replica for a in acts] == [1, 0, 2]
        assert [a.at for a in acts] == [2.5, 4.0, 1.0]
        assert acts[2].factor == 3.0

    def test_percent_form_resolves_against_horizon(self):
        (a,) = parse_serve_fault_plan("kill:replica=0,at=40%")
        assert a.at is None and a.at_frac == pytest.approx(0.4)
        assert a.resolve_at(10.0) == pytest.approx(4.0)
        with pytest.raises(FaultPlanError, match="horizon"):
            a.resolve_at(None)

    def test_plain_seconds_and_empty_plan(self):
        (a,) = parse_serve_fault_plan("stall:replica=1,at=0.25,secs=2")
        assert a.at == 0.25 and a.secs == 2.0
        assert parse_serve_fault_plan("") == []
        assert parse_serve_fault_plan("  ;  ") == []

    @pytest.mark.parametrize("plan, match", [
        ("boom:replica=0,at=1s", "kind"),
        ("kill:replica=0", "at= are required"),
        ("kill:at=1s", "replica= and at="),
        ("kill:replica=-1,at=1s", ">= 0"),
        ("kill:replica=0,at=eventually", "not a time"),
        ("kill:replica=0,at=nan", "finite"),
        ("kill:replica=0,at=1e999", "finite"),
        ("kill:replica=0,at=150%", "0%..100%"),
        ("stall:replica=0,at=1s,secs=nan", "> 0"),
        ("slow:replica=0,at=1s,factor=nan", "finite"),
        ("slow:replica=0,at=1s", "factor"),
        ("slow:replica=0,at=1s,factor=0.5", ">= 1"),
        ("kill:replica=0,at=1s,factor=2", "only applies to"),
        ("kill:replica=0,at=1s,secs=2", "only applies to"),
        ("stall:replica=0,at=1s,secs=0", "> 0"),
    ])
    def test_malformed_plans_fail_fast(self, plan, match):
        with pytest.raises(FaultPlanError, match=match):
            parse_serve_fault_plan(plan)

    def test_fleet_validates_replica_ids_at_arm_time(self, params):
        clk = FakeClock()
        fl = _fleet(params, clk)
        with pytest.raises(FaultPlanError, match="outside this fleet"):
            fl.arm_fault_plan("kill:replica=7,at=1s")

    def test_hand_built_actions_validated_at_arm_time(self, params):
        """Actions built in code (the documented Sequence input path)
        get the parser's fail-fast contract: a malformed one raises
        FaultPlanError at ARM time, never a TypeError out of the
        fleet loop at fire time."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        with pytest.raises(FaultPlanError, match="finite factor"):
            fl.arm_fault_plan(
                [ServeFaultAction(kind="slow", replica=0, at=1.0)])
        with pytest.raises(FaultPlanError, match="exactly one"):
            fl.arm_fault_plan(
                [ServeFaultAction(kind="kill", replica=0)])
        # a valid hand-built action arms fine
        fl.arm_fault_plan(
            [ServeFaultAction(kind="kill", replica=0, at=1.0)])


# ------------------------------------------------------------- router


class _StubEngine:
    def __init__(self, free, occ, slots=2):
        self._free, self._occ = free, occ
        self.config = ServeConfig(decode_slots=slots, page_size=8,
                                  num_pages=32)

        class _Cache:
            def __init__(self, occ):
                self._occ = occ

            def occupancy(self):
                return self._occ

            def fits(self, lp, mn):
                return lp + mn <= 64

        self.cache = _Cache(occ)

    def _free_slots(self):
        return self._free


class _StubReplica:
    def __init__(self, rid, free, occ, state="healthy", assigned=0):
        self.id = rid
        self.state = state
        self.engine = _StubEngine(free, occ)
        self.assigned = [object()] * assigned

    @property
    def healthy(self):
        return self.state == "healthy"


class TestRouter:
    def _req(self):
        return Request(prompt=np.arange(4, dtype=np.int32), max_new_tokens=4)

    def test_most_free_slots_wins(self):
        reps = [_StubReplica(0, 0, 0.2), _StubReplica(1, 2, 0.9)]
        assert pick_replica(reps, self._req()).id == 1

    def test_occupancy_breaks_slot_ties(self):
        reps = [_StubReplica(0, 1, 0.8), _StubReplica(1, 1, 0.1)]
        assert pick_replica(reps, self._req()).id == 1

    def test_in_flight_breaks_cold_start_ties(self):
        reps = [_StubReplica(0, 2, 0.0, assigned=1),
                _StubReplica(1, 2, 0.0, assigned=0)]
        assert pick_replica(reps, self._req()).id == 1

    def test_dead_and_saturated_replicas_ineligible(self):
        dead = _StubReplica(0, 2, 0.0, state="dead")
        # in_flight_limit = decode_slots + 1 = 3
        full = _StubReplica(1, 0, 0.5, assigned=3)
        assert not eligible(dead, self._req())
        assert not eligible(full, self._req())
        assert pick_replica([dead, full], self._req()) is None

    def test_retry_after_hint(self):
        assert retry_after_hint(5, 4, [], 0.05) == 0.05
        hint = retry_after_hint(3, 2, [1.0, 3.0], 0.05)
        assert hint == pytest.approx((3 + 1) * 2.0 / 2)
        assert retry_after_hint(0, 0, [1.0], 0.25) == 0.25


# ------------------------------------------------- rebase (recompute)


class TestRebase:
    def test_folds_generated_into_prompt_output_untouched(self):
        req = Request(prompt=np.arange(5, dtype=np.int32),
                      max_new_tokens=6)
        req.generated = [7, 8, 9]
        req.output = [7, 8, 9]
        req.prefill_pos = 5
        assert rebase_for_recompute(req)
        assert list(req.prompt) == [0, 1, 2, 3, 4, 7, 8, 9]
        assert req.max_new_tokens == 3
        assert req.generated == [] and req.output == [7, 8, 9]
        assert req.prefill_pos == 0
        # the sampling fold position only ever counts ORIGINAL prompt
        # + emitted tokens: stable across any number of rebases.
        assert req.sample_index == 5 + 3

    def test_nothing_left_to_generate(self):
        req = Request(prompt=np.arange(3, dtype=np.int32),
                      max_new_tokens=2)
        req.generated = [1, 2]
        req.output = [1, 2]
        assert not rebase_for_recompute(req)


# ------------------------------------------------------ fleet basics


class TestFleetBasics:
    def test_all_finish_and_match_lm_decode(self, params):
        clk = FakeClock()
        fl = _fleet(params, clk)
        spec = [(5, 6), (9, 4), (3, 8), (7, 5)]
        reqs = [fl.submit(_prompt(i, lp), n)
                for i, (lp, n) in enumerate(spec)]
        while not fl.idle:
            fl.step()
            clk.t += 0.001
        for i, ((lp, n), req) in enumerate(zip(spec, reqs)):
            assert req.state == "finished"
            assert req.output == _ref(params, _prompt(i, lp), n)
        st = fl.stats()
        assert st["by_state"] == {"finished": 4}
        f = st["fleet"]
        assert f["replicas"] == 2 and f["healthy"] == 2
        assert f["incidents"] == [] and f["redispatched"] == 0
        assert len(f["per_replica"]) == 2
        for cell in f["per_replica"]:
            assert {"id", "state", "free_slots", "occupancy",
                    "in_flight", "steps", "restarts"} <= set(cell)
        # both replicas actually served (the router spread the load)
        assert all(c["steps"] > 0 for c in f["per_replica"])

    def test_heartbeat_dirs_namespaced_per_fleet(self, params, tmp_path):
        base = str(tmp_path / "hb")
        f1 = _fleet(params, FakeClock(), heartbeat_dir=base,
                    watchdog_timeout=30.0)
        f2 = _fleet(params, FakeClock(), heartbeat_dir=base,
                    watchdog_timeout=30.0)
        assert f1.heartbeat_dir != f2.heartbeat_dir
        assert os.path.dirname(f1.heartbeat_dir) == base
        assert os.path.dirname(f2.heartbeat_dir) == base

    def test_close_removes_heartbeat_dir_and_is_idempotent(
            self, params, tmp_path):
        base = str(tmp_path / "hb")
        with _fleet(params, FakeClock(), heartbeat_dir=base) as fl:
            hb = fl.heartbeat_dir
            assert os.path.isdir(hb)
        assert not os.path.exists(hb)   # context exit closed it
        fl.close()                       # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            fl.step()

    def test_reset_metrics_requires_idle_and_clears(self, params):
        clk = FakeClock()
        fl = _fleet(params, clk)
        req = fl.submit(_prompt(0, 5), 3)
        with pytest.raises(RuntimeError, match="in flight"):
            fl.reset_metrics()
        while not fl.idle:
            fl.step()
            clk.t += 0.001
        assert req.state == "finished"
        fl.reset_metrics()
        st = fl.stats()
        assert st["requests"] == 0 and st["fleet"]["redispatched"] == 0


# ------------------------------------------- drain/redispatch (kill)


class TestKillRedispatch:
    def _run_with_kill(self, params, spec, temps=None, kill_after=6):
        """Clean + faulted fleet over identical submissions; the
        faulted one loses replica 1 after ``kill_after`` warm steps.
        Returns (clean_reqs, faulted_reqs, faulted_fleet)."""
        outs = []
        for faulted in (False, True):
            clk = FakeClock()
            fl = _fleet(params, clk, max_restarts=2)
            reqs = [fl.submit(_prompt(10 + i, lp), n,
                              temperature=(temps[i] if temps else 0.0),
                              seed=17 + i)
                    for i, (lp, n) in enumerate(spec)]
            if faulted:
                for _ in range(kill_after):
                    fl.step()
                    clk.t += 0.001
                victims = list(fl.replicas[1].assigned)
                assert victims, "kill must catch in-flight work"
                assert any(len(r.generated) > 0 for r in victims), \
                    "kill must catch a request mid-DECODE"
                fl.arm_fault_plan("kill:replica=1,at=0s")
            while not fl.idle:
                fl.step()
                clk.t += 0.001
            outs.append((reqs, fl))
        (clean_reqs, _), (faulted_reqs, fl) = outs
        return clean_reqs, faulted_reqs, fl

    def test_greedy_bit_identical_to_fault_free_run(self, params):
        spec = [(5, 8), (9, 6), (3, 10), (7, 7), (4, 9), (6, 5)]
        clean, faulted, fl = self._run_with_kill(params, spec)
        f = fl.stats()["fleet"]
        assert f["incidents_by_class"] == {"crashed": 1}
        assert f["redispatched"] >= 1
        assert f["tokens_recomputed"] > 0
        assert f["restarts_used"] == 1
        inc = f["incidents"][0]
        assert inc["category"] == "crashed" and inc["code"] == -9
        for i, (rc, rf) in enumerate(zip(clean, faulted)):
            assert rf.state == "finished", (i, rf.state)
            # the at-most-once + bit-exactness acceptance pin
            assert rf.output == rc.output, i
            # and the clean run itself equals lm_decode
            assert rc.output == _ref(params, _prompt(10 + i, spec[i][0]),
                                     spec[i][1])
        assert any(r.redispatches > 0 for r in faulted)
        # redispatched requests carry NO page bookkeeping from the dead
        # engine (its allocator died with it)
        for r in faulted:
            if r.redispatches:
                assert r.pages == []

    def test_sampled_requests_resume_exact_stream(self, params):
        """temperature>0: the position-folded sampling keys make even
        stochastic streams redispatch-exact (the fleet preserves
        orig_prompt_len/output, so sample_index never drifts)."""
        spec = [(5, 8), (9, 6), (3, 10), (7, 7)]
        temps = [0.0, 0.9, 0.7, 0.0]
        clean, faulted, fl = self._run_with_kill(params, spec,
                                                temps=temps)
        assert fl.stats()["fleet"]["redispatched"] >= 1
        for i, (rc, rf) in enumerate(zip(clean, faulted)):
            assert rf.state == "finished"
            assert rf.output == rc.output, i

    def test_drain_routes_uncollected_terminal_requests(self, params):
        """A request that reached a terminal state in the very step
        that killed its replica (engine raised after finishing it,
        before the end-of-tick collect) must land in the matching
        FLEET list — never be dropped from stats."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        rep = fl.replicas[0]
        fin = Request(prompt=np.arange(4, dtype=np.int32),
                      max_new_tokens=2)
        fin.state = "finished"
        fin.output = [1, 2]
        out = Request(prompt=np.arange(4, dtype=np.int32),
                      max_new_tokens=2)
        out.state = "timeout"
        rep.assigned = [fin, out]
        moved, _ = fl._drain(rep, clk())
        assert moved == 0
        assert fin in fl.finished and out in fl.timed_out
        # and never double-appended on a second defensive pass
        rep.assigned = [fin]
        fl._drain(rep, clk())
        assert sum(1 for r in fl.finished if r is fin) == 1

    def test_engine_exception_is_a_classified_crash(self, params):
        """A REAL exception escaping one replica's engine step (engine
        bug, allocator error, OOM) is a replica incident — classified
        ``crashed``, drained, relaunched — never a fleet-wide abort
        (one replica is one failure domain)."""
        clk = FakeClock()
        fl = _fleet(params, clk, max_restarts=2)
        spec = [(5, 8), (9, 6), (3, 10), (7, 7)]
        reqs = [fl.submit(_prompt(10 + i, lp), n)
                for i, (lp, n) in enumerate(spec)]
        refs = [_ref(params, _prompt(10 + i, lp), n)
                for i, (lp, n) in enumerate(spec)]
        for _ in range(4):
            fl.step()
            clk.t += 0.001
        assert fl.replicas[1].assigned

        def boom():
            raise RuntimeError("device OOM")

        fl.replicas[1].engine.step = boom
        fl.step()           # must NOT raise
        clk.t += 0.001
        assert fl.replicas[1].state == "dead"
        while not fl.idle:
            fl.step()
            clk.t += 0.001
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref
        f = fl.stats()["fleet"]
        assert f["incidents_by_class"] == {"crashed": 1}
        assert f["incidents"][0]["code"] == 1

    def test_killed_on_last_token_finishes_without_reemit(self, params):
        """A request drained with nothing left to generate (its last
        token was already emitted) must FINISH with exactly its emitted
        stream — the at-most-once guarantee's edge case: never a
        re-queue that would re-emit."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        req = Request(prompt=np.arange(5, dtype=np.int32),
                      max_new_tokens=2)
        req.generated = [3, 4]
        req.output = [3, 4]
        req.state = "decode"
        rep = fl.replicas[0]
        rep.assigned.append(req)
        moved, recomputed = fl._drain(rep, clk())
        assert moved == 0 and recomputed == 2
        assert req.state == "finished"
        assert req.output == [3, 4]
        assert req in fl.finished
        assert req not in fl.queue


# ------------------------------------------------- stall -> watchdog


class TestStallWatchdog:
    def test_stall_watchdog_classified_relaunch(self, params):
        """e2e on the REAL clock: a stalled replica stops heartbeating,
        the PR-9 HealthWatchdog kills it, the incident classes
        ``stalled`` (not a hang, not a generic crash), and the fleet
        still finishes every request bit-exact."""
        spec = [(5, 8), (9, 6), (3, 10), (7, 7)]
        refs = [_ref(params, _prompt(10 + i, lp), n)
                for i, (lp, n) in enumerate(spec)]
        fl = ServeFleet(params, _cfg(), FleetConfig(
            replicas=2, max_restarts=2, backoff_base=0.01,
            watchdog_timeout=0.4))
        reqs = [fl.submit(_prompt(10 + i, lp), n)
                for i, (lp, n) in enumerate(spec)]
        for _ in range(5):
            fl.step()
        assert fl.replicas[0].assigned, "stall must strand work"
        fl.arm_fault_plan("stall:replica=0,at=0s")
        fl.run(max_steps=100000)
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref
        f = fl.stats()["fleet"]
        assert f["incidents_by_class"] == {"stalled": 1}
        assert f["incidents"][0]["category"] == "stalled"
        assert f["detect_s"] is not None and f["detect_s"] >= 0.4
        assert f["restarts_used"] == 1

    def test_bounded_stall_resumes_without_watchdog(self, params):
        """A stall SHORTER than any watchdog: the replica simply
        resumes — no incident, no relaunch, everything finishes."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        reqs = [fl.submit(_prompt(i, 5), 4) for i in range(4)]
        for _ in range(3):
            fl.step()
            clk.t += 0.001
        fl.arm_fault_plan("stall:replica=0,at=0s,secs=0.05")
        while not fl.idle:
            fl.step()
            clk.t += 0.01
        assert all(r.state == "finished" for r in reqs)
        f = fl.stats()["fleet"]
        assert f["incidents"] == [] and f["restarts_used"] == 0


# ------------------------------------------------------- slow faults


class TestSlowFault:
    def test_slow_replica_sleeps_factor_minus_one(self, params):
        """A slow:factor=F replica pays (F-1) x its measured step time
        as extra latency — the degraded-host shape the router's
        least-loaded policy steers around."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        fl.submit(_prompt(0, 5), 4)
        fl.arm_fault_plan("slow:replica=0,at=0s,factor=3")
        sleeps = []

        def spy_sleep(dt):
            sleeps.append(dt)
            clk.sleep(dt)

        fl._sleep = spy_sleep
        rep0 = fl.replicas[0]
        real_step = rep0.engine.step

        def timed_step():
            out = real_step()
            clk.t += 0.004          # the engine step "took" 4 ms
            return out

        rep0.engine.step = timed_step
        fl.step()
        assert rep0.slow_factor == 3.0
        assert sleeps and sleeps[-1] == pytest.approx(0.008)

    def test_slow_factor_applied_and_reset_on_kill(self, params):
        clk = FakeClock()
        fl = _fleet(params, clk)
        fl.arm_fault_plan("slow:replica=1,at=0s,factor=2; "
                          "kill:replica=1,at=0.5s")
        fl.submit(_prompt(0, 5), 3)
        fl.step()
        assert fl.replicas[1].slow_factor == 2.0
        clk.t += 1.0
        fl.step()
        assert fl.replicas[1].state in ("dead", "failed")
        assert fl.replicas[1].slow_factor == 1.0


# ----------------------------------------------------- load shedding


class TestLoadShedding:
    def test_truth_table_and_allocator_conservation(self, params):
        clk = FakeClock()
        cfg = _cfg(decode_slots=1)
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=1, max_queue=2,
                                    max_restarts=0),
                        clock=clk, sleep=clk.sleep)
        p = _prompt(0, 5)
        rs = [fl.submit(p, 4) for _ in range(8)]
        # bounded queue: 2 queued, the rest shed as overloaded
        assert [r.state for r in rs] == ["queued"] * 2 + ["rejected"] * 6
        for r in rs[2:]:
            assert r.reject_reason == "overloaded"
            assert r.retry_after is not None and r.retry_after > 0
        # infeasible: can never run on this geometry; no retry hint
        big = fl.submit(_prompt(1, LMAX), 10)
        assert big.state == "rejected"
        assert big.reject_reason == "infeasible"
        assert big.retry_after is None
        # the conservation pin: rejected requests never touched any
        # replica, so not one KV page is held anywhere
        for rep in fl.replicas:
            assert rep.engine.cache.allocator.in_use == 0
        st = fl.stats()["fleet"]
        assert st["shed"] == 6
        assert st["rejected_by_reason"] == {"overloaded": 6,
                                            "infeasible": 1}

    def test_rejected_is_terminal_and_counted_in_stats(self, params):
        clk = FakeClock()
        fl = ServeFleet(params, _cfg(),
                        FleetConfig(replicas=1, max_queue=1,
                                    max_restarts=0),
                        clock=clk, sleep=clk.sleep)
        a = fl.submit(_prompt(0, 5), 3)
        b = fl.submit(_prompt(1, 5), 3)
        assert a.state == "queued" and b.state == "rejected"
        st = fl.stats()
        assert st["by_state"]["rejected"] == 1
        assert st["by_state"]["queued"] == 1

    def test_engine_max_queue_holds_at_router_not_terminal(self, params):
        """Regression (review finding): with the ENGINE's own bounded
        queue configured (a standalone-engine knob), the router must
        hold backlog at the fleet head until the replica frees up —
        not dispatch into a full engine queue and terminally shed; and
        no reject may ever be double-counted between the engine's and
        the fleet's lists."""
        clk = FakeClock()
        cfg = _cfg(decode_slots=2, max_queue=1)
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=1, max_restarts=0),
                        clock=clk, sleep=clk.sleep)
        rs = [fl.submit(_prompt(i, 5), 3) for i in range(4)]
        fl.step()
        # nothing terminally rejected: the engine queue bound only
        # slows dispatch, it never sheds
        assert fl.rejected == []
        assert fl.replicas[0].engine.scheduler.rejected == []
        st = fl.stats()
        assert st["requests"] == 4, st["by_state"]
        while not fl.idle:
            fl.step()
            clk.t += 0.001
        assert all(r.state == "finished" for r in rs)
        st = fl.stats()
        assert st["requests"] == 4
        assert st["by_state"] == {"finished": 4}
        assert st["fleet"]["shed"] == 0

    def test_fleet_queue_ttl_expires_waiting_requests(self, params):
        """A request can blow its deadline WAITING at the router —
        before any replica ever saw it; the fleet-level sweep times it
        out (each engine sweeps its own in-service requests)."""
        clk = FakeClock()
        fl = ServeFleet(params, _cfg(decode_slots=1),
                        FleetConfig(replicas=1, max_restarts=0),
                        clock=clk, sleep=clk.sleep)
        # saturate the only replica's in-flight headroom (limit =
        # decode_slots + 1 = 2) with long generations...
        busy = [fl.submit(_prompt(i, 5), 20) for i in range(2)]
        fl.step()
        clk.t += 0.001
        # ...so the TTL'd request is stuck in the FLEET queue
        req = fl.submit(_prompt(7, 5), 3, ttl=0.5)
        fl.step()
        assert req.state == "queued" and req in fl.queue
        clk.t += 1.0
        fl.step()
        assert req.state == "timeout"
        assert req in fl.timed_out and req not in fl.queue
        assert fl.stats()["fleet"]["timeout"] == 1
        assert all(r.state != "timeout" for r in busy)


# ------------------------------------------- budget, backoff, degrade


class TestRestartPolicy:
    def test_exponential_backoff_schedule(self, params):
        clk = FakeClock(t=100.0)
        fl = _fleet(params, clk, replicas=1, max_restarts=3,
                    backoff_base=0.2, backoff_cap=10.0)
        rep = fl.replicas[0]
        fl.arm_fault_plan("kill:replica=0,at=0s")
        fl.step()
        assert rep.state == "dead"
        assert rep.relaunch_at == pytest.approx(clk.t + 0.2)
        # not due yet: no relaunch
        clk.t += 0.1
        fl.step()
        assert rep.state == "dead"
        clk.t += 0.2
        fl.step()
        assert rep.state == "healthy" and rep.restarts == 1
        # second kill backs off twice as long
        fl.arm_fault_plan("kill:replica=0,at=0s")
        fl.step()
        assert rep.relaunch_at == pytest.approx(clk.t + 0.4)

    def test_budget_exhaustion_fails_replica_and_sheds(self, params):
        clk = FakeClock()
        fl = _fleet(params, clk, replicas=1, max_restarts=0,
                    max_queue=0)
        rs = [fl.submit(_prompt(i, 5), 3) for i in range(3)]
        fl.step()                      # dispatch
        clk.t += 0.001
        fl.arm_fault_plan("kill:replica=0,at=0s")
        fl.step()                      # kill + drain
        clk.t += 1.0
        fl.step()                      # relaunch due -> budget gone
        rep = fl.replicas[0]
        assert rep.state == "failed"
        assert not fl.alive
        # everything unfinished was shed (never silently stranded)
        assert all(r.state in ("rejected", "finished") for r in rs)
        shed = [r for r in rs if r.state == "rejected"]
        assert shed and all(r.reject_reason == "overloaded"
                            for r in shed)
        # and a post-mortem submit sheds immediately, no hint
        late = fl.submit(_prompt(9, 5), 3)
        assert late.state == "rejected"
        assert late.reject_reason == "overloaded"
        assert late.retry_after is None
        assert fl.idle   # terminated, not hung

    def test_watchdog_kill_record_cleared_on_relaunch(self, params):
        """The watchdog's per-replica kill memo must not mute watching
        the NEXT incarnation (the supervisor resets per attempt; the
        fleet clears per relaunch)."""
        clk = FakeClock()
        fl = _fleet(params, clk, replicas=2, max_restarts=2,
                    watchdog_timeout=30.0)
        assert fl.watchdog is not None
        fl.watchdog.kills[1] = 5.0     # as if the watchdog killed it
        fl._kill_replica(fl.replicas[1], code=-9, stalled=True,
                         now=clk.t, detect_age=5.0)
        clk.t += 1.0
        fl.step()
        assert fl.replicas[1].state == "healthy"
        assert 1 not in fl.watchdog.kills


# ---------------------------------------------- versioned rolling updates


class TestVersionedRollingUpdate:
    """update_params() on the inproc fleet: drain → swap → readmit one
    replica at a time, with the version pin making a mid-stream weight
    mix impossible — a request decodes ENTIRELY under one params
    version, across redispatch included."""

    @pytest.fixture(scope="class")
    def params2(self):
        return plm.init_lm_params(jax.random.PRNGKey(7), V, LMAX,
                                  LAYERS, H, DH, FFN)

    def _drain(self, fl, clk):
        guard = 0
        while not fl.idle or fl.update_active:
            if not fl.step():
                clk.sleep(0.02)
            guard += 1
            assert guard < 3000, "fleet failed to drain"

    def test_update_rolls_fleet_streams_stay_single_version(
            self, params, params2):
        clk = FakeClock()
        fl = _fleet(params, clk)
        try:
            p0, p1 = _prompt(20, 6), _prompt(21, 6)
            r0 = fl.submit(p0, 8)
            for _ in range(3):
                fl.step()
            assert r0.version == 1 and r0.output
            assert fl.update_params(params2) == 2
            with pytest.raises(RuntimeError, match="in progress"):
                fl.update_params(params2)
            r1 = fl.submit(p1, 8)
            self._drain(fl, clk)
            # r0 was mid-stream at the roll: its pin means its WHOLE
            # output is the old model's, bit-identical to lm_decode
            assert r0.state == "finished"
            assert r0.output == _ref(params, p0, 8)
            # r1 landed during the roll: either version is legal, but
            # only ENTIRELY one of them
            assert r1.output in (_ref(params, p1, 8),
                                 _ref(params2, p1, 8))
            f = fl.stats()["fleet"]
            assert f["params_version"] == 2
            assert not f["update_active"]
            assert all(r["version"] == 2 for r in f["per_replica"])
            assert len({r["params_sha"]
                        for r in f["per_replica"]}) == 1
            assert f["incidents_by_class"] == {}
            # post-roll submissions can only decode the new weights
            p2 = _prompt(22, 6)
            r2 = fl.submit(p2, 8)
            self._drain(fl, clk)
            assert r2.output == _ref(params2, p2, 8)
        finally:
            fl.close()

    def test_redispatch_rebases_only_onto_same_version(self, params):
        """Both replicas on v1: a kill mid-decode redispatches with
        the rebase (at-most-once), version pin intact."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        try:
            p = _prompt(23, 6)
            r = fl.submit(p, 10)
            for _ in range(4):
                fl.step()
            assert r.version == 1 and r.output
            victim = next(rep for rep in fl.replicas
                          if any(q is r for q in rep.assigned))
            fl.arm_fault_plan(f"kill:replica={victim.id},at=0s")
            self._drain(fl, clk)
            assert r.redispatches == 1
            assert r.version == 1 and r.version_restarts == 0
            assert r.output == _ref(params, p, 10)
        finally:
            fl.close()

    def test_stranded_version_restarts_from_scratch(self, params,
                                                    params2):
        """The explicit cross-version policy: the ONLY v1 replica dies
        mid-stream while the fleet has already rolled to v2 — the
        pinned request can never continue (no v1 replica will ever
        exist again), so it RESTARTS from its original prompt under v2
        and its full stream is the new model's."""
        clk = FakeClock()
        fl = _fleet(params, clk, replicas=1, max_restarts=2)
        try:
            p = _prompt(24, 6)
            r = fl.submit(p, 10)
            for _ in range(4):
                fl.step()
            assert r.version == 1 and r.output
            fl.update_params(params2)
            # kill the (only) v1 replica before its drain completes:
            # its relaunch wire-inits from the CURRENT artifact (v2)
            fl.arm_fault_plan("kill:replica=0,at=0s")
            self._drain(fl, clk)
            assert r.state == "finished"
            assert r.version_restarts == 1
            assert r.version == 2
            assert fl.version_recomputed == 1
            # the restart is a FULL stream under v2 — never a splice
            # of v1 and v2 tokens
            assert r.output == _ref(params2, p, 10)
            assert len(r.output) == 10
        finally:
            fl.close()

    def test_updating_replica_stops_accepting_but_fleet_serves(
            self, params, params2):
        """Zero-downtime means the drained replica's traffic routes to
        its peers: while replica 0 drains, a new request must dispatch
        to replica 1 — never queue behind the roll."""
        clk = FakeClock()
        fl = _fleet(params, clk)
        try:
            p = _prompt(25, 6)
            r0 = fl.submit(p, 30)
            for _ in range(3):
                fl.step()
            fl.update_params(params2)
            fl.step()   # picks the draining replica
            draining = [rep for rep in fl.replicas
                        if not rep.accepting]
            assert len(draining) == 1
            r1 = fl.submit(_prompt(26, 5), 3)
            fl.step()
            serving = next(rep for rep in fl.replicas
                           if any(q is r1 for q in rep.assigned))
            assert serving is not draining[0]
            self._drain(fl, clk)
            assert r0.state == r1.state == "finished"
        finally:
            fl.close()

    def test_update_on_inproc_requires_no_wire_faults(self, params):
        fl = _fleet(params, FakeClock())
        try:
            with pytest.raises(FaultPlanError, match="params-push"):
                fl.arm_fault_plan("transfer:replica=0,at=1s")
            with pytest.raises(FaultPlanError, match="params-push"):
                fl.arm_fault_plan("corrupt:replica=0,at=1s")
        finally:
            fl.close()

    def test_wrong_geometry_update_raises_before_any_mutation(
            self, params):
        clk = FakeClock()
        fl = _fleet(params, clk)
        try:
            bad = plm.init_lm_params(jax.random.PRNGKey(5), V,
                                     LMAX // 2, LAYERS, H, DH, FFN)
            with pytest.raises(ValueError, match="geometry"):
                fl.update_params(bad)
            # structure matters too, not just leaf shapes: a renamed
            # key with identical leaves is a different model
            renamed = dict(params)
            renamed["embedding"] = renamed.pop("embed")
            with pytest.raises(ValueError, match="geometry"):
                fl.update_params(renamed)
            # NOTHING mutated: no roll armed, version/artifact intact,
            # and the fleet still serves
            assert not fl.update_active
            assert fl.params_version == 1
            assert fl.params is params
            r = fl.submit(_prompt(27, 6), 4)
            self._drain(fl, clk)
            assert r.output == _ref(params, _prompt(27, 6), 4)
        finally:
            fl.close()


class TestSpeculativeFleet:
    """Speculation rides ServeConfig, so every fleet replica builds a
    speculative engine with NO fleet-layer changes — re-prove the kill/
    redispatch exactness pin with speculate_k on: a redispatched
    request re-prefills on the survivor, resumes mid-stream under
    speculative windows, and still emits the lm_decode stream."""

    def test_kill_redispatch_bit_exact_under_spec(self, params):
        spec = [(5, 8), (9, 6), (3, 10), (7, 7)]
        cfg = _cfg(speculate_k=2, draft_layers=1)
        outs = []
        for faulted in (False, True):
            clk = FakeClock()
            fl = _fleet(params, clk, cfg=cfg, max_restarts=2)
            reqs = [fl.submit(_prompt(10 + i, lp), n)
                    for i, (lp, n) in enumerate(spec)]
            if faulted:
                for _ in range(4):
                    fl.step()
                    clk.t += 0.001
                victims = list(fl.replicas[1].assigned)
                assert victims, "kill must catch in-flight work"
                fl.arm_fault_plan("kill:replica=1,at=0s")
            while not fl.idle:
                fl.step()
                clk.t += 0.001
            outs.append((reqs, fl))
        (clean_reqs, _), (faulted_reqs, fl) = outs
        assert fl.stats()["fleet"]["redispatched"] >= 1
        for i, (rc, rf) in enumerate(zip(clean_reqs, faulted_reqs)):
            assert rf.state == "finished", (i, rf.state)
            assert rf.output == rc.output, i
            assert rc.output == _ref(params, _prompt(10 + i, spec[i][0]),
                                     spec[i][1])
        # both fleets actually speculated (every replica stamps spec)
        assert any(rep.engine.spec_stats() is not None
                   and rep.engine.spec_stats()["ticks"] > 0
                   for rep in fl.replicas if rep.engine is not None)
