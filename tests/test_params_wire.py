"""Transfer-codec coverage for the wire-native weight distribution
(horovod_tpu/serve/params_wire.py).

The tentpole's codec contract, pinned exhaustively on tiny artifacts:

* the blob container is DETERMINISTIC (identical params -> identical
  bytes -> one sha256 — content addressing is what the digest-verify
  and the bit-identical-weights pin hang off);
* every chunk-truncation prefix is a typed ``FrameError`` (never a
  mis-parse, never a silent short write);
* every single-bit flip of a chunk payload is a typed
  ``ChecksumError`` (the per-chunk CRC riding inside the frame codec);
* a manifest/whole-artifact digest mismatch is a typed rejection with
  NO partial load (the temp is removed, the final path never exists);
* resume-from-offset is exact: a transfer torn at any chunk boundary
  (or mid-chunk) resumes into a bit-identical artifact.
"""

import hashlib
import os

import numpy as np
import pytest

from horovod_tpu.serve import params_wire as pw
from horovod_tpu.serve.transport import ChecksumError, FrameError

PARAMS = {
    "embed": np.arange(24, dtype=np.float32).reshape(4, 6),
    "layers": [
        {"w": np.full((3, 3), 2.5, np.float32),
         "b": np.arange(3, dtype=np.int32)},
        {"w": np.eye(3, dtype=np.float32) * -1.25,
         "b": np.asarray([7, 8, 9], np.int32)},
    ],
    "pos": np.linspace(0, 1, 8, dtype=np.float32).reshape(8, 1),
}

CHUNK = 64


def _manifest(blob, version=1, chunk_bytes=CHUNK):
    return pw.make_manifest(blob, version=version,
                            chunk_bytes=chunk_bytes)


# ----------------------------------------------------------------- blob


class TestBlob:
    def test_roundtrip_bit_exact(self):
        blob = pw.params_to_blob(PARAMS)
        out = pw.params_from_blob(blob, as_jax=False)
        assert list(out) == list(PARAMS)
        np.testing.assert_array_equal(out["embed"], PARAMS["embed"])
        np.testing.assert_array_equal(out["layers"][1]["b"],
                                      PARAMS["layers"][1]["b"])
        assert out["layers"][0]["w"].dtype == np.float32
        assert out["layers"][0]["b"].dtype == np.int32

    def test_deterministic_bytes_and_digest(self):
        # np.savez would stamp zip timestamps; this container must not.
        b1, b2 = pw.params_to_blob(PARAMS), pw.params_to_blob(PARAMS)
        assert b1 == b2
        assert pw.sha256_hex(b1) == hashlib.sha256(b2).hexdigest()

    def test_garbage_and_torn_blobs_are_typed(self):
        blob = pw.params_to_blob(PARAMS)
        with pytest.raises(FrameError, match="magic"):
            pw.params_from_blob(b"XXXX" + blob[4:], as_jax=False)
        with pytest.raises(FrameError, match="torn"):
            pw.params_from_blob(blob[:len(blob) // 2], as_jax=False)
        with pytest.raises(FrameError, match="trailing"):
            pw.params_from_blob(blob + b"\x00", as_jax=False)

    def test_manifest_math(self):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        assert m["total_bytes"] == len(blob)
        assert m["num_chunks"] == -(-len(blob) // CHUNK)
        assert m["sha256"] == hashlib.sha256(blob).hexdigest()
        assert len(m["leaves"]) == 6   # embed + 2x(w, b) + pos
        assert m["leaves"][0] == {"shape": [4, 6], "dtype": "float32"}


# ---------------------------------------------------------------- chunks


class TestChunkCodec:
    def test_chunks_cover_the_blob_exactly(self):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        raw = b"".join(pw.check_chunk(m, pw.make_chunk(blob, m, i))[1]
                       for i in range(m["num_chunks"]))
        assert raw == blob

    def test_every_truncation_prefix_is_typed(self):
        """Fuzz: every proper prefix of a chunk's payload must resolve
        as a typed FrameError (size mismatch — a torn chunk can never
        be written as if complete)."""
        import base64

        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        chunk = pw.make_chunk(blob, m, 1)
        raw = base64.b64decode(chunk["data"])
        for k in range(len(raw)):
            torn = dict(chunk, data=base64.b64encode(raw[:k])
                        .decode("ascii"))
            with pytest.raises(FrameError):
                pw.check_chunk(m, torn)

    def test_every_bit_flip_is_checksum_error(self):
        """Fuzz: flipping any single bit of a chunk payload must be a
        typed ChecksumError (the per-chunk CRC, independent of the
        transport frame's own CRC)."""
        import base64

        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        chunk = pw.make_chunk(blob, m, 0)
        raw = bytearray(base64.b64decode(chunk["data"]))
        for byte in range(len(raw)):
            for bit in (0, 7):
                mutated = bytearray(raw)
                mutated[byte] ^= 1 << bit
                bad = dict(chunk, data=base64.b64encode(bytes(mutated))
                           .decode("ascii"))
                with pytest.raises(ChecksumError):
                    pw.check_chunk(m, bad)

    def test_structural_corruptions_are_typed(self):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        chunk = pw.make_chunk(blob, m, 0)
        with pytest.raises(FrameError, match="version"):
            pw.check_chunk(m, dict(chunk, version=2))
        with pytest.raises(FrameError, match="outside"):
            pw.check_chunk(m, dict(chunk, index=m["num_chunks"]))
        with pytest.raises(FrameError, match="offset"):
            pw.check_chunk(m, dict(chunk, offset=CHUNK))
        with pytest.raises(FrameError, match="payload"):
            pw.check_chunk(m, dict(chunk, data="!!not-base64!!"))
        with pytest.raises(FrameError, match="malformed"):
            pw.check_chunk(m, {"index": 0})
        with pytest.raises(FrameError):
            pw.check_chunk(m, "not a dict")


# ------------------------------------------------------------- assembler


class TestAssembler:
    def _push_all(self, asm, blob, m, start=0):
        for i in range(start, m["num_chunks"]):
            asm.write_chunk(pw.make_chunk(blob, m, i))

    def test_happy_path_digest_and_atomic_commit(self, tmp_path):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob, version=3)
        asm = pw.ArtifactAssembler(str(tmp_path))
        assert asm.begin(m) == 0
        self._push_all(asm, blob, m)
        path, sha = asm.commit()
        assert sha == m["sha256"]
        assert open(path, "rb").read() == blob
        assert "v3" in os.path.basename(path)
        # the temp is gone: commit is a rename, not a copy
        assert not [p for p in os.listdir(str(tmp_path))
                    if p.endswith(".part")]

    def test_digest_mismatch_rejects_with_no_partial_load(self, tmp_path):
        blob = pw.params_to_blob(PARAMS)
        m = dict(_manifest(blob), sha256="0" * 64)
        asm = pw.ArtifactAssembler(str(tmp_path))
        asm.begin(m)
        self._push_all(asm, blob, m)
        with pytest.raises(ChecksumError, match="no partial load"):
            asm.commit()
        # NOTHING loadable exists: no final artifact, no temp either
        assert os.listdir(str(tmp_path)) == []

    def test_commit_of_incomplete_assembly_is_typed(self, tmp_path):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        asm = pw.ArtifactAssembler(str(tmp_path))
        asm.begin(m)
        self._push_all(asm, blob, m)
        del asm
        short = pw.ArtifactAssembler(str(tmp_path))
        short.begin(m)
        # fresh begin resumed at full size... simulate a short one
        m2 = _manifest(blob, version=2)
        asm2 = pw.ArtifactAssembler(str(tmp_path))
        asm2.begin(m2)
        asm2.write_chunk(pw.make_chunk(blob, m2, 0))
        with pytest.raises(FrameError, match="incomplete"):
            asm2.commit()

    def test_resume_from_offset_is_exact(self, tmp_path):
        """The torn-transfer resume: k chunks land, the sender dies, a
        NEW attempt begins — begin() reports the verified prefix, the
        remainder streams, and the committed bytes are bit-identical
        to the never-torn artifact."""
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        k = m["num_chunks"] // 2
        first = pw.ArtifactAssembler(str(tmp_path))
        first.begin(m)
        for i in range(k):
            first.write_chunk(pw.make_chunk(blob, m, i))
        resumed = pw.ArtifactAssembler(str(tmp_path))
        assert resumed.begin(m) == k * CHUNK
        self._push_all(resumed, blob, m, start=k)
        path, sha = resumed.commit()
        assert sha == m["sha256"]
        assert open(path, "rb").read() == blob

    def test_partial_trailing_chunk_is_truncated_not_trusted(self,
                                                            tmp_path):
        """A writer that died MID-chunk leaves a partial tail; begin()
        floors to the last whole-chunk boundary and the resume is
        still exact."""
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        first = pw.ArtifactAssembler(str(tmp_path))
        first.begin(m)
        first.write_chunk(pw.make_chunk(blob, m, 0))
        tmp = [p for p in os.listdir(str(tmp_path))
               if p.endswith(".part")][0]
        with open(os.path.join(str(tmp_path), tmp), "ab") as f:
            f.write(b"\x01\x02\x03")   # torn mid-chunk garbage
        resumed = pw.ArtifactAssembler(str(tmp_path))
        assert resumed.begin(m) == CHUNK   # floored, garbage dropped
        self._push_all(resumed, blob, m, start=1)
        path, sha = resumed.commit()
        assert open(path, "rb").read() == blob

    def test_non_contiguous_chunk_is_typed(self, tmp_path):
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        asm = pw.ArtifactAssembler(str(tmp_path))
        asm.begin(m)
        with pytest.raises(FrameError, match="non-contiguous"):
            asm.write_chunk(pw.make_chunk(blob, m, 1))

    def test_protocol_misuse_is_typed(self, tmp_path):
        asm = pw.ArtifactAssembler(str(tmp_path))
        blob = pw.params_to_blob(PARAMS)
        m = _manifest(blob)
        with pytest.raises(FrameError, match="begin"):
            asm.write_chunk(pw.make_chunk(blob, m, 0))
        with pytest.raises(FrameError, match="begin"):
            asm.commit()
        with pytest.raises(FrameError):
            pw.ArtifactAssembler(str(tmp_path)).begin(
                {"version": 1})   # malformed manifest


class TestPruneArtifacts:
    def test_superseded_versions_and_temps_pruned(self, tmp_path):
        blob = pw.params_to_blob(PARAMS)
        committed = []
        for v in (1, 2):
            m = _manifest(blob, version=v)
            asm = pw.ArtifactAssembler(str(tmp_path))
            asm.begin(m)
            for i in range(m["num_chunks"]):
                asm.write_chunk(pw.make_chunk(blob, m, i))
            committed.append(asm.commit()[0])
        # a stale temp from an abandoned transfer
        stale = tmp_path / "params-v9.deadbeefdead.part"
        stale.write_bytes(b"xx")
        other = tmp_path / "unrelated.bin"
        other.write_bytes(b"yy")
        pw.prune_artifacts(str(tmp_path), committed[-1])
        left = sorted(p.name for p in tmp_path.iterdir())
        assert os.path.basename(committed[-1]) in left
        assert os.path.basename(committed[0]) not in left
        assert stale.name not in left
        assert other.name in left   # only artifact-shaped files pruned
