"""Test harness: an 8-device virtual CPU mesh.

The reference ran its suite under ``mpirun -np N`` so the same tests covered
size 1 and size N (reference test/common.py:25-58). The TPU-native
equivalent: force the JAX host platform to expose 8 virtual CPU devices and
run every SPMD test over that mesh — sharding semantics (psum, all_gather,
shard_map partitioning) are platform-independent, so what compiles and
passes here compiles on a v5e slice.

Note: this image's sitecustomize imports jax at interpreter startup (axon
PJRT plugin), so JAX_PLATFORMS in the shell env is already consumed;
``jax.config.update`` is the reliable override, and XLA_FLAGS is still read
lazily at first backend init.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def hvd():
    import horovod_tpu.jax as hvd

    hvd.init()
    return hvd
