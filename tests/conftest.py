"""Test harness: an 8-device virtual CPU mesh.

The reference ran its suite under ``mpirun -np N`` so the same tests covered
size 1 and size N (reference test/common.py:25-58). The TPU-native
equivalent: force the JAX host platform to expose 8 virtual CPU devices and
run every SPMD test over that mesh — sharding semantics (psum, all_gather,
shard_map partitioning) are platform-independent, so what compiles and
passes here compiles on a v5e slice.

Note: this image's sitecustomize imports jax at interpreter startup (axon
PJRT plugin), so JAX_PLATFORMS in the shell env is already consumed;
``jax.config.update`` is the reliable override, and XLA_FLAGS is still read
lazily at first backend init.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Two-lane suite strategy. The full suite (default) is the CI gate; on a
# single-CPU box it runs ~25 min, dominated by whole-program integration
# tests (subprocess launches, example smokes, big-model compiles).
# `pytest -m "not slow"` is the fast iteration lane — measured
# 2026-07-31 (round 4): 9.8 min / 255 tests on the 1-core box (17.9 min
# before the round-4 re-budget) — that keeps per-op/per-kernel
# closed-form and exactness tests and skips whole-program wrappers and
# whole-MODEL composition pins whose internals those tests already
# cover (each demotion below names its faster stand-ins; the full lane
# still runs everything). Auto-marked here (one registry) instead of
# per-file decorators.
_SLOW_TESTS = {
    "test_bench.py::test_default_lane_contract",
    "test_bench.py::test_lm_lane_contract[dense-default]",
    "test_bench.py::test_lm_lane_contract[r3-flags]",
    "test_bench.py::test_zero_composes_with_lm_lane",
    "test_bench.py::test_compile_only_lane_contract",
    "test_bench.py::test_lm_flash_attention_lane",
    "test_bench.py::test_hung_backend_degrades_to_error_json",
    "test_bench.py::test_crashing_child_degrades_to_error_json",
    "test_bench.py::test_sigterm_mid_run_still_emits_contract_line",
    "test_examples_models.py::TestExamples::test_flax_imagenet_resnet50_smoke",
    "test_examples_models.py::TestExamples::test_jax_transformer_zero_smoke",
    "test_examples_models.py::TestExamples::test_jax_gpt_parallel_smoke",
    "test_examples_models.py::TestExamples::test_long_context_ring_attention_smoke",
    "test_examples_models.py::TestExamples::test_jax_mnist",
    "test_examples_models.py::TestExamples::test_torch_mnist_via_launcher",
    "test_examples_models.py::TestExamples::test_tf_keras_mnist_via_launcher",
    "test_examples_models.py::TestExamples::test_torch_synthetic_benchmark_via_launcher",
    "test_examples_models.py::TestModelZoo::test_forward_shapes[inception_v3-shape1]",
    "test_conv_bn.py::TestFusedResNet::test_inception_fused_matches_unfused",
    "test_examples_models.py::TestModelZoo::test_vgg16_train_step_runs",
    "test_models.py::test_graft_entry_multichip_subprocess",
    "test_multiprocess_spmd.py::test_two_process_global_mesh_end_to_end",
    "test_multiprocess_spmd.py::test_two_process_hierarchical_ladder",
    "test_multiprocess_spmd.py::test_four_process_global_mesh_end_to_end",
    "test_multiprocess_spmd.py::test_four_process_hierarchical_ladder",
    "test_multiprocess_spmd.py::test_eight_process_asymmetric_ladder_and_ulysses",
    "test_tf_binding.py::TestMultiProcess::test_ops",
    "test_tf_binding.py::TestMultiProcess::test_distributed_gradient_tape_converges",
    "test_tf_binding.py::TestMultiProcess::test_keras_callbacks",
    "test_launcher.py::TestCLI::test_restarts_relaunches_until_success",
    "test_launcher.py::TestCLI::test_restarts_exhausted_returns_failure",
    "test_examples_models.py::TestExamples::test_jax_word2vec_smoke",
    # Whole-program serving bench wrappers (subprocess, ~15-20s each);
    # stand-ins: tests/test_serve_engine.py exactness/lifecycle pins
    # (fast) + the tools/check.sh serve smoke lane runs the contract.
    "test_serve_bench.py::TestServeBenchContract::test_continuous_record_contract",
    "test_serve_bench.py::TestServeBenchContract::test_ab_record_carries_both_sides",
    # ~10s, same subprocess shape; stand-in: the in-process
    # test_serve_engine.py::TestLifecycle::test_hard_reject_when_never_fits
    "test_serve_bench.py::TestServeBenchContract::test_require_finished_fails_loudly",
    # Round-4 re-budget (fast lane had crept to 17.9 min): whole-model
    # composition pins whose per-op internals have fast stand-ins.
    # 57s; stand-ins: test_parallel.py TestMoE per-token closed forms
    "test_parallel_lm.py::test_moe_lm_matches_dense_routing",
    # 41s; stand-ins: test_train_step_matches_dense + decode_composes_with_tp
    "test_parallel_lm.py::test_decode_matches_naive_recompute",
    # 28s; stand-ins: the per-axis exactness pins in the same file
    "test_parallel_lm.py::test_bf16_composed_step_and_decode",
    # 26s; stand-ins: test_zero.py equivalence + ring-attention exactness
    "test_parallel_lm.py::test_zero_composes_with_sequence_parallel",
    # 30s (two full-model compiles); stand-in: LM lane contract (slow)
    "test_models.py::test_scan_layers_matches_unrolled",
    # 25s (two full training runs); numerics covered by optax contract
    "test_models.py::test_bf16_momentum_tracks_fp32",
    # 29s whole-ResNet step; stand-ins: the kernel-level exactness tests
    # (test_fused_equals_unfused_f32, *_grads_equal_*) in the same file
    "test_conv_bn.py::TestFusedResNet::test_resnet50_style_step_fused_vs_unfused",
    # 42s public-API wrapper; mechanism covered by the native-lane
    # TestSubCommunicator tests (fast)
    "test_torch_binding.py::TestMultiProcess::test_init_comm_subworld",
    # np=2 variants stay fast; the larger sizes are integration depth
    "test_torch_binding.py::TestMultiProcess::test_ops[3]",
    "test_native_core.py::TestMultiProcess::test_collectives[4]",
    # 20s whole-ViT step; stand-in: vit forward-shape test
    "test_examples_models.py::TestModelZoo::test_vit_spmd_train_step",
    # Sanitizer builds recompile all of csrc/ (~60s each) and rerun the
    # stress binary under TSAN/ASAN; the plain stress test (fast lane)
    # covers deadlock/corruption, these cover races/memory. Run via
    # tools/check.sh --sanitize or pytest -m slow.
    "test_native_stress.py::test_stress_clean_under_tsan",
    "test_native_stress.py::test_stress_clean_under_asan",
    # The windowed elastic e2e repeats the whole-job kill/relaunch wrapper
    # at k=3; the k=1 variant (fast lane) covers the same supervision
    # path, and TestRunElastic::test_resume_is_bit_exact_windowed pins
    # the windowed resume numerics in-process.
    "test_elastic.py::TestEndToEnd::test_kill_rank1_resumes_bit_exact[3]",
    # ~25s: traces the FULL hvdverify registry (9 big-model gate lanes).
    # Fast stand-in: test_repo_sweep_core_is_clean covers the
    # optimizer/parallel/elastic programs; the gate lanes run here and
    # in tools/check.sh --verify.
    "test_hvdverify.py::test_repo_sweep_is_clean",
    # ~65s, two whole-bench subprocess runs; stand-ins: the in-process
    # wire-summary/layout pins (test_hierarchical.py) and the traced
    # per-leg byte conservation (test_wire_bytes.py hierarchical
    # params) cover the stamp math — this wrapper pins only the JSON
    # plumbing, like the other slow-marked bench contract tests.
    "test_bench.py::test_hierarchical_wire_stamp_in_record",
    # ~35s: three 24-step LM trainings (fp32 / fp8+EF / fp8 no-EF).
    # Fast stand-ins: test_error_feedback_time_average_converges pins
    # the EF mechanics and test_ef_exact_codec_leaves_zero_residual the
    # Average composition; the LM trajectory pin runs in the CI gate
    # and tools/check.sh's full lane.
    "test_hierarchical.py::test_ef_convergence_small_lm",
    # Round-10 re-budget: the fast lane had grown to ~18 min on the
    # 1-core box (the 870 s tier-1 window truncated it mid-suite, which
    # is worse than demoting — a timeout drops ~170 later tests
    # arbitrarily). Same discipline as round 4: whole-program
    # subprocess wrappers whose internals have fast in-process
    # stand-ins move to the slow lane (still in the full CI gate).
    # 55s whole-bench flash A/B wrapper; stand-ins: the packed-vs-full
    # grid exactness + grid-table pins in test_parallel.py
    # TestFlashAttention (fast) cover the kernels, this pins JSON
    # plumbing like its slow-marked bench siblings.
    "test_bench.py::test_lm_flash_grid_stamp_and_full_grid_ab",
    # 33s / 20s / 18s whole-bench subprocess wrappers; stand-ins:
    # test_elastic.py snapshot pins, ops/attention crossover constants,
    # and the overlap/bucket-plan pins in test_overlap.py +
    # tests/test_scaling_model.py respectively.
    "test_bench.py::test_snapshot_stamp_in_record",
    "test_bench.py::test_lm_attention_auto_policy",
    "test_bench.py::test_overlap_and_bucket_stamps_in_record",
    # ~25s whole-bench subprocess wrapper (a real LM lane + a degraded
    # attempt-timeout run); stand-in: the parser-level --mesh
    # canonicalization + mesh_cell pins in
    # test_mesh_flag_canonicalizes_and_rejects_invalid (fast).
    "test_bench.py::test_mesh_stamp_in_record",
    # 42s TF keras multi-process wrapper; its three TestMultiProcess
    # siblings are already slow-marked with the same justification
    # (single-process keras coverage stays fast).
    "test_tf_binding.py::TestMultiProcess::test_keras_lr_callbacks_and_load_model",
    # 30s + 20s: the even-vocab (32/8) vocab-parallel xent pair (the
    # ragged 28/8 pair joined them in round 17 — see below).
    "test_xent.py::TestVocabParallel::test_loss_and_grads_match_dense[32-8]",
    "test_xent.py::TestVocabParallel::test_loss_and_grads_match_dense_in_region[32-8]",
    # 30s + 24s torch multi-process integration depth; test_ops[2] and
    # the single-process optimizer tests stay fast (test_ops[3] was
    # already slow-marked on the same grounds).
    "test_torch_binding.py::TestMultiProcess::test_distributed_optimizer_converges",
    "test_torch_binding.py::TestMultiProcess::test_optimizer_features",
    # 22s + 11s serving-bench subprocess wrappers: their two sibling
    # contract tests are already slow-marked (stand-ins:
    # test_serve_engine exactness matrix + the check.sh serve smoke,
    # which runs BOTH attention modes end-to-end).
    "test_serve_bench.py::TestServeBenchContract::test_attention_paged_record_contract",
    "test_serve_bench.py::TestServeBenchContract::test_ab_attention_record_carries_both_sides",
    # 25s + 10s fleet-bench subprocess wrappers (each runs whole
    # clean/faulted fleets): stand-ins are the in-process
    # TestKillRedispatch::test_greedy_bit_identical_to_fault_free_run
    # pin (fast) and the check.sh fleet smoke, which runs the exact
    # acceptance command end-to-end. Arg-validation stays fast.
    "test_serve_bench.py::TestFleetBenchContract::test_fleet_fault_ab_record_contract",
    "test_serve_bench.py::TestFleetBenchContract::test_fleet_clean_record_contract",
    # ~90s: whole clean+faulted PROCESS fleets (4 real worker spawns,
    # each paying the jax import + compile). Stand-ins: the fast
    # test_serve_worker.py::TestStubFleet matrix + the synthetic
    # fleet_cell pin; the check.sh process-fleet smoke runs this exact
    # command end-to-end.
    "test_serve_bench.py::TestFleetBenchContract::test_fleet_process_transport_record_contract",
    # 11s + 8s + 7s fleet composition depth: the fast greedy kill pin
    # already runs a clean fleet (== lm_decode per request) AND a
    # faulted fleet on the same submissions; the sampled variant
    # re-runs the same machinery at temperature>0 (engine-level
    # sampling recompute exactness is pinned fast in
    # test_serve_engine), and the stall e2e needs real wall-clock
    # heartbeat aging (watchdog unit pins + TestRestartPolicy stay
    # fast).
    "test_serve_fleet.py::TestFleetBasics::test_all_finish_and_match_lm_decode",
    "test_serve_fleet.py::TestKillRedispatch::test_sampled_requests_resume_exact_stream",
    "test_serve_fleet.py::TestStallWatchdog::test_stall_watchdog_classified_relaunch",
    # 14s whole-CLI launch wrapper; the TestRunFn in-process launcher
    # tests (identity env, collectives through the launcher) stay fast,
    # and the restart-path CLI tests were already slow-marked.
    "test_launcher.py::TestCLI::test_launch_command_success",
    # Round-17 re-budget (fast lane at ~900s > the 870s window): the
    # ragged 28/8 pair joins its even 32/8 twin — the through-boundary
    # variant had grown to 55s — so the whole vocab-parallel grads
    # matrix is slow-lane/CI-gate. Fast stand-ins:
    # test_loss_identical_on_every_rank (the vocab-parallel loss pin,
    # every rank, stays fast) and the dense fused-CE matrix incl. the
    # ragged 60/16 pad path (test_fused_ce_matches_dense).
    "test_xent.py::TestVocabParallel::test_loss_and_grads_match_dense[28-8]",
    "test_xent.py::TestVocabParallel::test_loss_and_grads_match_dense_in_region[28-8]",
    # 12s 4-process launcher collective round-trip; test_identity_env
    # pins the in-process launcher plumbing fast, and the elastic e2e
    # lanes drive launch_job with real collectives every run.
    "test_launcher.py::TestRunFn::test_collectives_through_launcher",
    # 14s: the longest serve-engine exactness matrix entry; the other
    # exactness classes (eviction-recompute, chunk-invariance, single
    # request, max_new=1) stay fast in both attention modes, and the
    # check.sh serve smoke re-pins greedy==lm_decode end-to-end.
    "test_serve_engine.py::TestGreedyExactness::test_staggered_joins_bit_identical[gather-tp1]",
    # Round-17 re-budget: the paged twin joins it on the same grounds
    # — the other exactness classes keep both attention modes fast.
    "test_serve_engine.py::TestGreedyExactness::test_staggered_joins_bit_identical[paged-tp1]",
    # The tp=4 staggered twins (6s each: SPMD compile + 6 lm_decode
    # refs) follow their tp1 parents to the slow lane; fast stand-ins
    # for staggered-under-TP are the tp4 single-request/eviction/
    # max_new exactness cells plus the check.sh TP smoke, which runs
    # a multi-request tp=4-vs-tp=1 A/B end-to-end.
    "test_serve_engine.py::TestGreedyExactness::test_staggered_joins_bit_identical[gather-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_staggered_joins_bit_identical[paged-tp4]",
    # Chunk-invariance under tp=4: chunk=4 (the ragged non-divisor)
    # stays fast in BOTH attention modes as the named stand-in; the
    # 1/3/16 tp4 cells (~3s each, 6 tests) are slow-lane — chunking
    # itself is pinned fast by the full tp1 chunk matrix, and the tp4
    # concern (SPMD prefill rows == lm_prefill rows) is chunk-size-
    # independent by construction.
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[1-gather-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[1-paged-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[3-gather-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[3-paged-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[16-gather-tp4]",
    "test_serve_engine.py::TestGreedyExactness::test_chunked_prefill_is_chunk_invariant[16-paged-tp4]",
    # 35s + 38s whole-bench ab-prefix subprocess wrappers (each runs a
    # cold AND a warm serve/fleet bench): stand-ins are the fast
    # in-process prefix pins — test_serve_prefix.py TestEngineHits
    # hit==cold==lm_decode and TestFleetPrefix co-location /
    # redispatch-savings — and the check.sh prefix smoke, which runs
    # the single-engine --ab-prefix contract end-to-end.
    "test_serve_bench.py::TestServeBenchContract::test_ab_prefix_record_contract",
    "test_serve_bench.py::TestFleetBenchContract::test_fleet_ab_prefix_record_contract",
    # ~20s whole-bench --ab-tp subprocess wrapper (tp=1 AND tp=4 SPMD
    # compiles): stand-ins are the fast in-process tp4 exactness cells
    # (test_serve_engine.py TestGreedyExactness mesh matrix +
    # TestTPSharding per-chip pins) and the check.sh TP smoke, which
    # runs the --ab-tp contract end-to-end; the cheap
    # test_ab_tp_arg_validation stays fast.
    "test_serve_bench.py::TestServeBenchContract::test_ab_tp_record_contract",
    # 13s np=2 torch multi-process ops: the torch TestMultiProcess
    # matrix goes fully slow-lane, matching the tf-binding precedent
    # (its whole TestMultiProcess class has been slow-marked for
    # rounds) — single-process torch op/optimizer tests stay fast.
    "test_torch_binding.py::TestMultiProcess::test_ops[2]",
    # 8s: the lazy-admission hit-stream twin; the reserve variant stays
    # fast and pins the same hit==cold==lm_decode exactness, and
    # test_admission_counts_only_missed_pages keeps the lazy-path
    # accounting fast.
    "test_serve_prefix.py::TestEngineHits::test_hit_stream_bit_identical_to_cold_and_lm_decode[lazy]",
    # 8s real wall-clock stall e2e (whole-job relaunch wrapper): the
    # kill[1] e2e stays fast covering the supervision path, and
    # test_native_core.py::TestStallDetection pins the watchdog
    # mechanics fast.
    "test_elastic.py::TestEndToEnd::test_stall_fault_terminates_via_watchdog",
    # 8s + 7s + 6s + 6s rolling-update/stall composition depth: the
    # core roll pin test_update_rolls_fleet_streams_stay_single_version
    # stays fast (clean roll, per-stream single-version), the stranded/
    # rebase/draining variants and the bounded-stall resume move to the
    # slow lane with the real-worker and tcp variants already there;
    # version-eligibility unit pins (TestRouter/TestRebase) stay fast.
    "test_serve_fleet.py::TestVersionedRollingUpdate::test_stranded_version_restarts_from_scratch",
    "test_serve_fleet.py::TestVersionedRollingUpdate::test_redispatch_rebases_only_onto_same_version",
    "test_serve_fleet.py::TestVersionedRollingUpdate::test_updating_replica_stops_accepting_but_fleet_serves",
    "test_serve_fleet.py::TestStallWatchdog::test_bounded_stall_resumes_without_watchdog",
    # 12s whole-tf.keras rewrap wrapper; the settings plumbing it pins
    # is asserted by the fast native-core knob tests, full run in CI.
    "test_review_regressions.py::test_tf_keras_rewrap_honors_new_settings",
    # 6s each native-lane forked-rank hierarchical variants; the core
    # ladder exactness (4ranks_2groups) and the degrade rules stay
    # fast, auth is covered by TestTransportAuth.
    "test_native_core.py::TestHierarchical::test_hierarchical_authenticated",
    "test_native_core.py::TestHierarchical::test_group_size_defaults_to_local_size",
    # ~20s each: real `python -m horovod_tpu.serve.worker` processes
    # (every spawn pays the sitecustomize jax import + first-step
    # compile). Fast stand-ins: test_serve_worker.py::TestStubFleet
    # runs the SAME fleet/transport code paths against real OS
    # processes via the no-jax protocol stub (~4s for the whole
    # recovery matrix incl. SIGKILL-classify, torn-frame, watchdog
    # stall, close-escalation), test_serve_transport.py pins the codec,
    # and the tools/check.sh process-fleet smoke runs the real-worker
    # kill e2e end to end.
    "test_serve_worker.py::TestRealWorkerE2E::test_kill_redispatch_bit_exact_vs_lm_decode",
    "test_serve_worker.py::TestRealWorkerE2E::test_stall_watchdog_classified_relaunch",
    "test_serve_worker.py::TestRealWorkerE2E::test_kill_mid_write_torn_frame_redispatch_exact",
    # Real-worker loopback-TCP partition e2e (round-14): same jax-spawn
    # cost as the others; fast stand-ins are
    # test_serve_fleet_tcp.py::TestStubTcpFleet (the whole host-domain
    # recovery matrix over real TCP via the no-jax stub) and the
    # tools/check.sh loopback-TCP fleet smoke.
    "test_serve_worker.py::TestRealWorkerE2E::test_tcp_partition_host_down_bit_exact_vs_lm_decode",
    # Round-15 rolling-update e2e (2 real tcp workers + an update push
    # = 4 jax imports + compiles). Fast stand-ins:
    # TestStubRollingUpdate (the full drain/push/tear/resume matrix on
    # the protocol stub) + TestVersionedRollingUpdate (inproc version
    # pinning vs lm_decode) + the check.sh rolling-update smoke.
    "test_serve_worker.py::TestRealWorkerE2E::test_tcp_rolling_update_torn_push_bit_exact_vs_lm_decode",
    # Round-19 speculative decoding (each spec cell pays the draft-
    # scan + verify-window compile, ~6-7s): the k=2 cells of the
    # exactness matrix stay fast in ALL FOUR attention×mesh
    # combinations as the named stand-ins — window math is
    # k-independent (the k=7 > steps clamp is pinned fast at the
    # model level by test_parallel_lm spec tests and at the engine
    # level by test_budget_clamp_never_overshoots).
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[1-gather-tp1]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[1-paged-tp1]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[1-gather-tp4]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[1-paged-tp4]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[4-gather-tp1]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[4-paged-tp1]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[4-gather-tp4]",
    "test_serve_engine.py::TestSpeculativeExactness::test_spec_stream_bit_identical[4-paged-tp4]",
    # 10s + 8s spec-composition depth: eviction-recompute and prefix/
    # COW under speculation re-run machinery whose non-spec twins
    # (TestGreedyExactness eviction matrix, TestTPSharding prefix/COW)
    # and spec twins (the k=2 matrix above, which exercises the SAME
    # widened page-grant/_cow_guard arithmetic every tick) stay fast;
    # the check.sh spec smoke runs the full contract end-to-end.
    "test_serve_engine.py::TestSpeculativeLifecycle::test_eviction_recompute_stays_exact_under_spec",
    "test_serve_engine.py::TestSpeculativeLifecycle::test_prefix_cow_stays_exact_under_spec",
    # 9s + 5s: two more spec engine compiles; fast stand-ins are the
    # host-side TestSpeculativeAcceptUnit rejection-sampling pins
    # (same speculative_accept code path, no compile) and the
    # non-spec TestSampling determinism/neighbor tests.
    "test_serve_engine.py::TestSpeculativeLifecycle::test_temperature_same_seed_deterministic",
    "test_serve_engine.py::TestSpeculativeLifecycle::test_greedy_neighbor_unaffected_by_sampling_slot",
    # ~3s each model-level spec windows at larger k: the [1-1]/[2-1]
    # cells stay fast and pin the same lm_decode_spec == lm_decode
    # equality; k=4/k=7 add only window width (and the k > steps
    # clamp, re-pinned fast by the engine budget-clamp test).
    "test_parallel_lm.py::test_spec_decode_matches_lm_decode[4-2]",
    "test_parallel_lm.py::test_spec_decode_matches_lm_decode[7-2]",
    # ~30s whole-bench --ab-spec subprocess wrapper (an OFF and an ON
    # serve lane + the bit-identity pin): stand-ins are the fast
    # test_ab_spec_arg_validation + the in-process spec exactness
    # matrix, and the check.sh spec smoke runs this exact command
    # (incl. the accept_rate==1.0 / tokens_per_step>1 record pins)
    # end-to-end.
    "test_serve_bench.py::TestServeBenchContract::test_ab_spec_record_contract",
    # ~26s clean+faulted fleet pair under speculation: the fast
    # TestKillRedispatch greedy pin covers drain/redispatch and the
    # spec matrix covers speculative exactness; this composition test
    # (redispatch resumes MID-STREAM under speculative windows) runs
    # in the CI gate.
    "test_serve_fleet.py::TestSpeculativeFleet::test_kill_redispatch_bit_exact_under_spec",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: whole-program integration wrapper; skipped by the fast "
        "iteration lane (pytest -m 'not slow'), always in the CI gate")


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        rel = item.nodeid.split("/")[-1]
        if rel in _SLOW_TESTS:
            matched.add(rel)
            item.add_marker(pytest.mark.slow)
    # Fail loudly on registry drift: a renamed/removed test would
    # otherwise silently rejoin the fast lane. Only enforced on full
    # collections (running a single file legitimately misses entries).
    stale = _SLOW_TESTS - matched
    if stale and len(items) > 200:
        raise pytest.UsageError(
            f"tests/conftest.py _SLOW_TESTS has stale entries: {stale}")


@pytest.fixture(scope="session")
def hvd():
    import horovod_tpu.jax as hvd

    hvd.init()
    return hvd
