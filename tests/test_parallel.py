"""Tests for TP / SP (ring + Ulysses) / PP / EP / hierarchical mesh over
the 8-device virtual CPU mesh. Every scheme is checked against a dense
single-device reference computation — the sharded result must match the
unsharded math, not merely run."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu.parallel as par
from horovod_tpu.ops.attention import dot_product_attention, flash_attention


def _mesh(axes):
    n = math.prod(abs(s) for s in axes.values())
    return par.make_mesh(axes, devices=jax.devices()[:n])


class TestMesh:
    def test_make_mesh_shapes(self, hvd):
        m = par.make_mesh({"dp": 2, "tp": 4})
        assert m.shape == {"dp": 2, "tp": 4}

    def test_make_mesh_wildcard(self, hvd):
        m = par.make_mesh({"dp": 2, "tp": -1})
        assert m.shape["tp"] == 4

    def test_make_mesh_bad_product(self, hvd):
        from horovod_tpu.common.exceptions import InvalidArgumentError

        with pytest.raises(InvalidArgumentError):
            par.make_mesh({"dp": 3, "tp": 3})

    def test_hierarchical_mesh(self, hvd):
        m = par.hierarchical_mesh(inner=4)
        assert m.shape == {"dcn": 2, "ici": 4}

    def test_hierarchical_allreduce_matches_flat(self, hvd):
        m = par.hierarchical_mesh(inner=4)
        x = jnp.arange(2 * 13, dtype=jnp.float32).reshape(2, 13)

        def fn(x):
            return par.hierarchical_allreduce(x, "dcn", "ici")

        # Grouped-psum replication the vma checker cannot infer
        # (lax.pcast to='invariant' is not implemented); scoped opt-out.
        out = jax.jit(jax.shard_map(fn, mesh=m, in_specs=P(),
                                    out_specs=P(), check_vma=False))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8,
                                   rtol=1e-6)

    def test_hierarchical_allreduce_average(self, hvd):
        m = par.hierarchical_mesh(inner=2)
        x = jnp.ones((5,), jnp.float32)
        out = jax.jit(jax.shard_map(
            lambda t: par.hierarchical_allreduce(t, average=True),
            mesh=m, in_specs=P(), out_specs=P(), check_vma=False))(x)
        np.testing.assert_allclose(np.asarray(out), np.ones(5), rtol=1e-6)


class TestHierarchicalKnobs:
    """HOROVOD_HIERARCHICAL_ALLREDUCE/ALLGATHER change the executed
    collective in the flagship SPMD lane (round-1 gap: parsed, never
    consulted). Reference semantics: operations.cc:1284-1436, :929-1032."""

    @pytest.fixture()
    def hier_config(self, hvd):
        from horovod_tpu.common.state import global_state

        cfg = global_state().config
        saved = (cfg.hierarchical_allreduce, cfg.hierarchical_allgather,
                 cfg.hierarchical_inner_size)
        cfg.hierarchical_allreduce = True
        cfg.hierarchical_allgather = True
        cfg.hierarchical_inner_size = 4  # 8 chips = 2 (dcn) x 4 (ici)
        yield cfg
        (cfg.hierarchical_allreduce, cfg.hierarchical_allgather,
         cfg.hierarchical_inner_size) = saved

    def test_fused_reduce_hierarchical_matches_flat(self, hvd, hier_config):
        from horovod_tpu.jax.fusion import fused_reduce

        def fn(x, y):
            a, b = fused_reduce([x, y], average=False)
            return a, b

        x = jnp.arange(8 * 6, dtype=jnp.float32).reshape(8, 6)
        y = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3) * 0.5
        a, b = hvd.spmd_run(fn, x, y, in_specs=(P("hvd"), P("hvd")),
                            out_specs=(P(), P()))
        # Sum over the 8 rank-shards of each tensor.
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(x).reshape(8, 1, 6).sum(0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(y).reshape(8, 1, 3).sum(0), rtol=1e-6)

    def test_knob_changes_lowered_collective(self, hvd, hier_config):
        """The knob must change the program XLA sees: the hierarchical
        ladder lowers to grouped reduce-scatter + two collectives, the
        flat path to one ungrouped all-reduce."""
        from horovod_tpu.common.state import global_state
        from horovod_tpu.jax.fusion import fused_reduce

        def fn(x):
            return fused_reduce([x], average=False)[0]

        x = jnp.ones((8, 16), jnp.float32)
        run = hvd.spmd_fn(fn, in_specs=P("hvd"), out_specs=P())
        hier_text = run._compiled.lower(x).as_text()
        assert "reduce_scatter" in hier_text, hier_text[-2000:]

        global_state().config.hierarchical_allreduce = False

        def fn2(x):
            return fused_reduce([x], average=False)[0]

        flat_text = hvd.spmd_fn(
            fn2, in_specs=P("hvd"), out_specs=P())._compiled.lower(x).as_text()
        assert "reduce_scatter" not in flat_text

    def test_hierarchical_allgather_matches_flat(self, hvd, hier_config):
        from horovod_tpu.common.state import global_state

        def fn(x):
            return hvd.allgather(x)

        x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
        hier = hvd.spmd_run(fn, x, in_specs=P("hvd"), out_specs=P())
        hier_text = hvd.spmd_fn(
            fn, in_specs=P("hvd"), out_specs=P())._compiled.lower(x).as_text()
        # Two-phase = two grouped all-gathers.
        assert hier_text.count("all_gather") >= 2, hier_text[-2000:]

        global_state().config.hierarchical_allgather = False

        def fn2(x):
            return hvd.allgather(x)

        flat = hvd.spmd_run(fn2, x, in_specs=P("hvd"), out_specs=P())
        np.testing.assert_array_equal(np.asarray(hier), np.asarray(flat))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        key = jax.random.PRNGKey(0)
        B, L, H, D = 2, 64, 2, 8
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_causal_block_q_not_multiple_of_block_k(self):
        """Regression: the causal loop bound must cover key blocks partially
        reached by a q-block when block_q % block_k != 0."""
        key = jax.random.PRNGKey(9)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (1, 48, 1, 8)) for i in range(3))
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=24)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_rectangular_blocks(self):
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (1, 32, 1, 4))
        k = jax.random.normal(jax.random.fold_in(key, 1), (1, 64, 1, 4))
        v = jax.random.normal(jax.random.fold_in(key, 2), (1, 64, 1, 4))
        ref = dot_product_attention(q, k, v)
        out = flash_attention(q, k, v, block_q=8, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    # Both backward implementations must be exact: the "auto" dispatch
    # routes small test shapes to the scan path, so every gradient test
    # pins the Pallas kernel split explicitly too (review r5: without
    # this, the ~200-line kernel backward had zero CI coverage).
    @pytest.mark.parametrize("bwd_impl", ["scan", "pallas"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_reference(self, causal, bwd_impl):
        """flash_attention is trainable: its custom-VJP blockwise
        backward must reproduce the dense reference's q/k/v gradients."""
        key = jax.random.PRNGKey(3)
        B, L, H, D = 2, 32, 2, 8
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))
        cot = jax.random.normal(jax.random.fold_in(key, 7), (B, L, H, D))

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) * cot)

        g_ref = jax.grad(loss(dot_product_attention), argnums=(0, 1, 2))(
            q, k, v)
        g_flash = jax.grad(
            loss(lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8,
                bwd_impl=bwd_impl)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("bwd_impl", ["scan", "pallas"])
    def test_gradients_block_q_not_multiple_of_block_k(self, bwd_impl):
        """Gradient twin of the partial-diagonal forward regression: the
        backward kernels' causal block-skip conditions must keep blocks
        PARTIALLY reached across an unaligned bq/bk diagonal."""
        key = jax.random.PRNGKey(11)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (1, 48, 1, 8)) for i in range(3))

        def f(fn):
            return lambda *a: jnp.sum(fn(*a) ** 2)

        g_ref = jax.grad(
            f(lambda q, k, v: dot_product_attention(q, k, v, causal=True)),
            argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(
            f(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                              block_q=16, block_k=24,
                                              bwd_impl=bwd_impl)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("bwd_impl", ["scan", "pallas"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_rectangular(self, causal, bwd_impl):
        """Lq < Lk (decode-style): with causal=True the key blocks past
        Lq are fully masked and skipped in the backward — the
        zero dk/dv tail must still match the dense reference."""
        key = jax.random.PRNGKey(4)
        q = jax.random.normal(key, (1, 16, 1, 4))
        k = jax.random.normal(jax.random.fold_in(key, 1), (1, 48, 1, 4))
        v = jax.random.normal(jax.random.fold_in(key, 2), (1, 48, 1, 4))

        def f(fn):
            return lambda *a: jnp.sum(fn(*a) ** 2)

        g_ref = jax.grad(
            f(lambda q, k, v: dot_product_attention(q, k, v, causal=causal)),
            argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(
            f(lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                              block_q=8, block_k=16,
                                              bwd_impl=bwd_impl)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_causal_grid_truncation_shape(self):
        """Causal square grids visit ONLY at-or-below-diagonal k-blocks:
        n(n+1)/2 of the n^2 full steps (the ~(n+1)/2n ratio), pinned on
        the step tables the packed grid scalar-prefetches and on the
        public accounting (flash_grid_info) bench.py stamps into its
        records."""
        from horovod_tpu.ops.attention import (_causal_step_tables,
                                               flash_grid_info)

        for n in (1, 2, 5, 8):
            g = flash_grid_info(n * 16, n * 16, causal=True, block_q=16,
                                block_k=16, head_dim=8)
            assert g["truncated"]
            assert g["steps"] == n * (n + 1) // 2
            assert g["steps_full"] == n * n
            assert g["kv_fetch_frac"] == round((n + 1) / (2 * n), 4)
        # Every enumerated pair intersects the mask's live region; the
        # k-major (dK/dV) walk enumerates exactly the same pairs.
        qi, kb = _causal_step_tables(8, 8, 16, 16)
        assert (kb * 16 <= qi * 16 + 15).all()
        qi_k, kb_k = _causal_step_tables(8, 8, 16, 16, k_major=True)
        assert qi_k.size == qi.size
        assert (set(zip(qi_k.tolist(), kb_k.tolist()))
                == set(zip(qi.tolist(), kb.tolist())))
        # Unaligned bq/bk diagonal (48 = 3x16 = 2x24): blocks PARTIALLY
        # reached across the diagonal stay enumerated.
        qi_u, kb_u = _causal_step_tables(3, 2, 16, 24)
        assert (kb_u * 24 <= qi_u * 16 + 15).all()
        assert qi_u.size == 3 + 1 + 1  # qi0->kb0, qi1->kb0..1, qi2->kb0..1
        # Non-causal, cross-attention (Lq != Lk), and offset-causal keep
        # the FULL grid; equal nonzero offsets are plain square causal.
        assert not flash_grid_info(64, 64, causal=False, block_q=8,
                                   block_k=8)["truncated"]
        assert not flash_grid_info(32, 64, causal=True, block_q=8,
                                   block_k=8)["truncated"]
        assert not flash_grid_info(64, 64, causal=True, q_offset=64,
                                   block_q=8, block_k=8)["truncated"]
        assert flash_grid_info(64, 64, causal=True, q_offset=128,
                               k_offset=128, block_q=8,
                               block_k=8)["truncated"]
        with pytest.raises(ValueError, match="truncate=True"):
            flash_grid_info(32, 64, causal=True, block_q=8, block_k=8,
                            truncate=True)

    def test_truncated_matches_full_grid(self):
        """The packed causal grid is bit-identical to the full grid's
        compute-skip path — forward AND the packed Pallas backward pair
        (truncate=False is the hw_sweep A/B lanes' pin)."""
        key = jax.random.PRNGKey(13)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (2, 64, 2, 8)) for i in range(3))
        out_t = flash_attention(q, k, v, causal=True, block_q=16,
                                block_k=16)
        out_f = flash_attention(q, k, v, causal=True, block_q=16,
                                block_k=16, truncate=False)
        np.testing.assert_array_equal(np.asarray(out_t), np.asarray(out_f))

        def loss(truncate):
            return lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=16, block_k=16,
                bwd_impl="pallas", truncate=truncate) ** 2)

        g_t = jax.grad(loss(None), argnums=(0, 1, 2))(q, k, v)
        g_f = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_t, g_f):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("bwd_impl", ["scan", "pallas"])
    def test_offset_causal_matches_reference(self, bwd_impl):
        """Global-offset causal (the ring/Ulysses shard geometry):
        queries are a suffix block at q_offset over a longer key range —
        the full-grid path with the shifted diagonal must match the
        dense reference for forward and both backward kernels."""
        key = jax.random.PRNGKey(17)
        q = jax.random.normal(key, (1, 16, 1, 8))
        k = jax.random.normal(jax.random.fold_in(key, 1), (1, 48, 1, 8))
        v = jax.random.normal(jax.random.fold_in(key, 2), (1, 48, 1, 8))
        ref = dot_product_attention(q, k, v, causal=True, q_offset=32)
        out = flash_attention(q, k, v, causal=True, q_offset=32,
                              block_q=8, block_k=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

        def f(fn):
            return lambda *a: jnp.sum(fn(*a) ** 2)

        g_ref = jax.grad(
            f(lambda q, k, v: dot_product_attention(q, k, v, causal=True,
                                                    q_offset=32)),
            argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(
            f(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                              q_offset=32, block_q=8,
                                              block_k=16,
                                              bwd_impl=bwd_impl)),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("bwd_impl", ["scan", "pallas"])
    def test_truncated_odd_seq_default_blocks(self, bwd_impl):
        """Seq not a multiple of the preferred block ladder (40 -> the
        8-sublane floor): the truncated causal path must stay exact vs
        dense through the degraded tiling, forward and both backwards."""
        key = jax.random.PRNGKey(19)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (2, 40, 2, 8)) for i in range(3))
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        g_ref = jax.grad(lambda q: jnp.sum(
            dot_product_attention(q, k, v, causal=True) ** 2))(q)
        g_fl = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=True, bwd_impl=bwd_impl) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_rejects_fully_masked_rows(self):
        """q_offset < k_offset leaves query rows with NO visible key —
        an undefined softmax where the kernel's 0-output would silently
        diverge from the dense reference's degenerate uniform rows. The
        contract is an explicit error, not a silent disagreement."""
        key = jax.random.PRNGKey(23)
        q = jax.random.normal(key, (1, 16, 1, 8))
        k = jax.random.normal(jax.random.fold_in(key, 1), (1, 16, 1, 8))
        with pytest.raises(ValueError, match="q_offset >= k_offset"):
            flash_attention(q, k, k, causal=True, k_offset=16,
                            block_q=8, block_k=8)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, hvd, causal):
        mesh = _mesh({"sp": 8})
        key = jax.random.PRNGKey(2)
        B, L, H, D = 2, 64, 2, 8  # L_local = 8
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))
        ref = dot_product_attention(q, k, v, causal=causal)

        out = jax.jit(jax.shard_map(
            lambda a, b, c: par.ring_attention(a, b, c, "sp", causal=causal),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp")))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_causal_dead_block_skip_matches_dense(self, hvd):
        """The causal dead-block skip (lax.cond over fully-above-diagonal
        visiting blocks) pinned against dense for forward AND gradients.
        Forced on explicitly: the auto gate disables it on legacy
        runtimes, where the rank-divergent cond only transposes inside
        check_vma=False regions — exactly how this test runs it, so the
        cond path has CI coverage on every runtime."""
        mesh = _mesh({"sp": 8})
        key = jax.random.PRNGKey(21)
        B, L, H, D = 2, 64, 2, 8
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))
        fn = jax.shard_map(
            lambda a, b, c: par.ring_attention(a, b, c, "sp", causal=True,
                                               skip_dead_blocks=True),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False)
        out = jax.jit(fn)(q, k, v)
        ref = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        g = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: jnp.sum(
            dot_product_attention(q, k, v, causal=True) ** 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-5)

    def test_grad_flows(self, hvd):
        mesh = _mesh({"sp": 4})
        key = jax.random.PRNGKey(3)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (1, 16, 1, 4))
                   for i in range(3))

        def loss_sharded(q, k, v):
            fn = jax.shard_map(
                lambda a, b, c: par.ring_attention(a, b, c, "sp",
                                                   causal=True),
                mesh=mesh, in_specs=P(None, "sp"),
                out_specs=P(None, "sp"))
            return jnp.sum(fn(q, k, v) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        g_sharded = jax.grad(loss_sharded)(q, k, v)
        g_dense = jax.grad(loss_dense)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_sharded),
                                   np.asarray(g_dense), atol=1e-4)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, hvd, causal):
        mesh = _mesh({"sp": 4})
        key = jax.random.PRNGKey(4)
        B, L, H, D = 2, 32, 4, 8  # H == axis size
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))
        ref = dot_product_attention(q, k, v, causal=causal)
        out = jax.jit(jax.shard_map(
            lambda a, b, c: par.ulysses_attention(a, b, c, "sp",
                                                  causal=causal),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp")))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_head_divisibility_error(self, hvd):
        mesh = _mesh({"sp": 8})
        q = jnp.zeros((1, 16, 4, 8))  # 4 heads < 8 ranks
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(jax.shard_map(
                lambda a: par.ulysses_attention(a, a, a, "sp"),
                mesh=mesh, in_specs=P(None, "sp"),
                out_specs=P(None, "sp")))(q)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_attn_fn_composes(self, hvd, causal):
        """The long-context flagship composition: after the head
        reshard, each chip runs FULL-sequence attention locally — which
        is exactly where the Pallas flash kernel belongs (attn_fn hook,
        ulysses_attention docstring). Forward AND gradients must match
        the dense reference; the kernel runs in interpret mode on the
        CPU mesh (class-1 check_vma opt-out, docs/parallelism.md)."""
        mesh = _mesh({"sp": 4})
        key = jax.random.PRNGKey(11)
        B, L, H, D = 2, 128, 4, 16  # flash blocks cover L after reshard
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, L, H, D))
                   for i in range(3))

        def flash(qh, kh, vh, causal, scale):
            return flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                   block_q=32, block_k=32)

        def loss_sharded(q, k, v):
            fn = jax.shard_map(
                lambda a, b, c: par.ulysses_attention(
                    a, b, c, "sp", causal=causal, attn_fn=flash),
                mesh=mesh, in_specs=P(None, "sp"),
                out_specs=P(None, "sp"), check_vma=False)
            return jnp.sum(fn(q, k, v) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, causal=causal) ** 2)

        np.testing.assert_allclose(
            float(jax.jit(loss_sharded)(q, k, v)),
            float(loss_dense(q, k, v)), rtol=1e-5)
        g_sharded = jax.grad(loss_sharded, (0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
        for gs, gd in zip(g_sharded, g_dense):
            np.testing.assert_allclose(np.asarray(gs), np.asarray(gd),
                                       atol=1e-4)


class TestTensorParallel:
    def test_mlp_matches_dense(self, hvd):
        mesh = _mesh({"tp": 8})
        key = jax.random.PRNGKey(5)
        Din, Dh, B = 16, 32, 4
        x = jax.random.normal(key, (B, Din))
        w_up = jax.random.normal(jax.random.fold_in(key, 1), (Din, Dh)) * 0.1
        b_up = jax.random.normal(jax.random.fold_in(key, 2), (Dh,)) * 0.1
        w_dn = jax.random.normal(jax.random.fold_in(key, 3), (Dh, Din)) * 0.1
        b_dn = jax.random.normal(jax.random.fold_in(key, 4), (Din,)) * 0.1

        dense = (jax.nn.gelu(x @ w_up + b_up)) @ w_dn + b_dn

        out = jax.jit(jax.shard_map(
            lambda x, wu, bu, wd, bd: par.tp_mlp(x, wu, bu, wd, bd, "tp"),
            mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None), P()),
            out_specs=P()))(x, w_up, b_up, w_dn, b_dn)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5)

    def test_column_gather_output(self, hvd):
        mesh = _mesh({"tp": 4})
        x = jnp.ones((2, 8))
        w = jnp.arange(8 * 12, dtype=jnp.float32).reshape(8, 12) * 0.01
        dense = x @ w
        # Tiled all_gather replication the vma checker cannot infer.
        out = jax.jit(jax.shard_map(
            lambda x, w: par.column_parallel(x, w, axis="tp",
                                             gather_output=True),
            mesh=mesh, in_specs=(P(), P(None, "tp")),
            out_specs=P(), check_vma=False))(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=1e-5)

    def test_shard_helpers(self, hvd):
        w = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
        np.testing.assert_array_equal(
            np.asarray(par.shard_columns(w, 3, 1)), np.asarray(w[:, 2:4]))
        np.testing.assert_array_equal(
            np.asarray(par.shard_rows(w, 2, 1)), np.asarray(w[2:]))


class TestPipeline:
    def test_matches_sequential(self, hvd):
        mesh = _mesh({"pp": 4})
        key = jax.random.PRNGKey(6)
        D, M, Bm = 8, 6, 2  # 6 microbatches of 2 rows
        # Stage p: x -> tanh(x @ W_p + b_p); stack over stages.
        ws = jax.random.normal(key, (4, D, D)) * 0.3
        bs = jax.random.normal(jax.random.fold_in(key, 1), (4, D)) * 0.1
        x = jax.random.normal(jax.random.fold_in(key, 2), (M, Bm, D))

        def stage(params, a):
            w, b = params
            return jnp.tanh(a @ w + b)

        expected = x
        for p in range(4):
            expected = jnp.tanh(expected @ ws[p] + bs[p])

        out = jax.jit(jax.shard_map(
            lambda params, x: par.pipeline_apply(stage, params, x, "pp"),
            mesh=mesh, in_specs=((P("pp"), P("pp")), P()),
            out_specs=P()))((ws, bs), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=1e-5)

    def test_gradients_match_sequential(self, hvd):
        """Pipeline gradients must equal the plain sequential autodiff —
        this pinned down a latent x(pp size) scaling from differentiating
        through the final raw psum (fixed via the exact-VJP sum_across)."""
        mesh = _mesh({"pp": 4})
        key = jax.random.PRNGKey(9)
        D, M, Bm = 8, 6, 2
        ws = jax.random.normal(key, (4, D, D)) * 0.3
        x = jax.random.normal(jax.random.fold_in(key, 1), (M, Bm, D))

        def stage(w, a):
            return jnp.tanh(a @ w)

        def seq_loss(ws):
            out = x
            for p in range(4):
                out = jnp.tanh(out @ ws[p])
            return jnp.mean(out ** 2)

        g_seq = jax.grad(seq_loss)(ws)

        def pipe_loss(ws, x):
            return jnp.mean(par.pipeline_apply(stage, ws, x, "pp") ** 2)

        g_pipe = jax.jit(jax.shard_map(
            jax.grad(pipe_loss), mesh=mesh, in_specs=(P("pp"), P()),
            out_specs=P("pp")))(ws, x)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                                   rtol=1e-5, atol=1e-6)

    def test_remat_gradients_match(self, hvd):
        """remat=True recomputes stage internals in backward; gradients
        must be identical to the stored-activation schedule."""
        mesh = _mesh({"pp": 4})
        key = jax.random.PRNGKey(8)
        D, M, Bm = 8, 6, 2
        ws = jax.random.normal(key, (4, D, D)) * 0.3
        x = jax.random.normal(jax.random.fold_in(key, 1), (M, Bm, D))

        def stage(w, a):
            return jnp.tanh(a @ w)

        def make_loss(remat):
            def loss(ws, x):
                out = par.pipeline_apply(stage, ws, x, "pp", remat=remat)
                return jnp.mean(out ** 2)

            return jax.jit(jax.shard_map(
                jax.grad(loss), mesh=mesh, in_specs=(P("pp"), P()),
                out_specs=P("pp")))

        g_plain = make_loss(False)(ws, x)
        g_remat = make_loss(True)(ws, x)
        np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_remat),
                                   rtol=1e-6, atol=1e-7)


def test_vma_checking_tracks_region(hvd):
    """Canary for the jax internal behind vma_checking(): the regime
    detector must read True/False inside matching shard_map regions —
    the typed/untyped gradient reductions branch on it, so a jax upgrade
    that moves the internal must fail THIS test loudly, not mis-scale
    gradients silently. On legacy runtimes with NO vma typing at all
    (jax.typeof absent; check_vma maps onto check_rep), the detector
    must report False in BOTH regions: the old rewrite machinery does
    not do the typed-regime cotangent reduction, so the untyped-branch
    reductions are the correct ones — pinned end-to-end by the
    dense-parity suites (tests/test_parallel_lm.py)."""
    from horovod_tpu.parallel._vma import vma_checking, vma_typing_available

    seen = {}

    def probe(key):
        def f(x):
            seen[key] = vma_checking()
            return x
        return f

    m = _mesh({"sp": 8})
    jax.jit(jax.shard_map(probe("typed"), mesh=m, in_specs=P(),
                          out_specs=P()))(jnp.ones((4,)))
    jax.jit(jax.shard_map(probe("untyped"), mesh=m, in_specs=P(),
                          out_specs=P(), check_vma=False))(jnp.ones((4,)))
    if vma_typing_available():
        assert seen == {"typed": True, "untyped": False}
    else:
        assert seen == {"typed": False, "untyped": False}


class TestMoE:
    def test_top1_routing_capacity(self, hvd):
        x = jnp.eye(4, dtype=jnp.float32)  # 4 tokens, 4 dims
        gate_w = jnp.eye(4) * 10.0  # token i -> expert i
        dispatch, combine, aux = par.top1_routing(x, gate_w, 4, 1)
        # Each expert receives exactly its token.
        np.testing.assert_allclose(np.asarray(jnp.sum(dispatch, axis=(0, 2))),
                                   np.ones(4))
        assert float(aux) > 0

    def test_moe_matches_per_token_formula(self, hvd):
        """With ample capacity (no drops), expert-parallel MoE must equal
        the per-token closed form: y[t] = gate[t] * expert_{e(t)}(x[t])."""
        mesh = _mesh({"ep": 4})
        key = jax.random.PRNGKey(7)
        T, D, E = 16, 8, 4
        x = jax.random.normal(key, (T, D))
        gate_w = jax.random.normal(jax.random.fold_in(key, 1), (D, E))
        ew = jax.random.normal(jax.random.fold_in(key, 2), (E, D, D)) * 0.3

        def expert_fn(w, tokens):
            return tokens @ w

        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        gate = jnp.max(probs, axis=-1)
        expected = jnp.einsum("t,td->td", gate,
                              jnp.einsum("td,tde->te", x, ew[eidx]))

        out = jax.jit(jax.shard_map(
            lambda x, gw, ew: par.moe_layer(x, gw, expert_fn, ew, "ep",
                                            capacity_factor=float(E)),
            mesh=mesh, in_specs=(P("ep"), P(), P("ep")),
            out_specs=P("ep")))(x, gate_w, ew)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=1e-5)

    def test_moe_multiple_experts_per_chip(self, hvd):
        """E=8 over 4 chips (e_local=2) exercises the (owner chip, local
        expert) reassembly of the return all_to_all."""
        mesh = _mesh({"ep": 4})
        key = jax.random.PRNGKey(8)
        T, D, E = 32, 4, 8
        x = jax.random.normal(key, (T, D))
        gate_w = jax.random.normal(jax.random.fold_in(key, 1), (D, E))
        ew = jax.random.normal(jax.random.fold_in(key, 2), (E, D, D)) * 0.3

        def expert_fn(w, tokens):
            return tokens @ w

        probs = jax.nn.softmax(x @ gate_w, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        gate = jnp.max(probs, axis=-1)
        expected = jnp.einsum("t,td->td", gate,
                              jnp.einsum("td,tde->te", x, ew[eidx]))

        out = jax.jit(jax.shard_map(
            lambda x, gw, ew: par.moe_layer(x, gw, expert_fn, ew, "ep",
                                            capacity_factor=float(E)),
            mesh=mesh, in_specs=(P("ep"), P(), P("ep")),
            out_specs=P("ep")))(x, gate_w, ew)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   atol=1e-5)
