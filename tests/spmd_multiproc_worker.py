"""Worker for the TRUE multi-process SPMD test: N processes x 2 virtual
CPU chips each, joined into ONE global mesh by ``hvd.init()`` through the
launcher's ``--jax`` mode (HOROVOD_JAX_COORDINATOR). Exercises the real
multi-host code paths — jax.distributed bootstrap, host-local<->global
conversion in spmd dispatch, cross-process collectives (Gloo), process
broadcast, and a full DistributedOptimizer training step.

Prints one RESULT line per process; the pytest driver asserts content and
cross-process equality.
"""

import os
import sys

# Chips per process (virtual): 2 by default; the np=8 lane runs 1 so the
# 8-way topology fits in 8 processes.
_LOCAL = int(os.environ.get("HVD_TEST_LOCAL_CHIPS", "2"))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_LOCAL}"
).strip()
import jax

jax.config.update("jax_platforms", "cpu")

import hashlib

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvd


def main() -> int:
    hvd.init()
    nproc = int(os.environ["HOROVOD_SIZE"])
    assert hvd.process_count() == nproc, (hvd.process_count(), nproc)
    assert hvd.size() == _LOCAL * nproc, hvd.size()
    assert hvd.local_size() == _LOCAL
    me = hvd.process_rank()

    # 1. Cross-process SPMD allreduce: per-process host-local shards in,
    # psum over ALL chips out. Process p's chips carry value p+1.
    x = jnp.full((_LOCAL, 3), float(me + 1), jnp.float32)
    out = hvd.spmd_run(
        lambda v: hvd.allreduce(v, average=False),
        x, in_specs=P("hvd"), out_specs=P("hvd"),
    )
    expected = float(_LOCAL) * sum(p + 1 for p in range(nproc))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)

    # 2. Eager process broadcast with a NON-ZERO root.
    got = hvd.broadcast(jnp.full((4,), float(me)), root_rank=1)
    np.testing.assert_allclose(np.asarray(got), 1.0)

    # 2b. Eager multi-process reducescatter (round-2 gap: raised
    # PreconditionError): process p contributes rows of value p+1, so
    # the summed tensor is uniform and each process keeps its dim-0
    # stripe of the sum (or the mean).
    rs_in = jnp.full((2 * nproc, 3), float(me + 1), jnp.float32)
    total = float(sum(p + 1 for p in range(nproc)))
    rs_sum = hvd.reducescatter(rs_in, average=False)
    assert rs_sum.shape == (2, 3), rs_sum.shape  # dim0 / nproc
    np.testing.assert_allclose(np.asarray(rs_sum), total, rtol=1e-6)
    rs_avg = hvd.reducescatter(rs_in, average=True)
    np.testing.assert_allclose(np.asarray(rs_avg), total / nproc, rtol=1e-6)

    # 2c. Eager multi-process alltoall (same round-2 gap): process p
    # sends split s the value 10*p + s; after the exchange process p
    # holds split p from every source — [10*0 + p, 10*1 + p, ...].
    a2a_in = jnp.concatenate(
        [jnp.full((2,), 10.0 * me + s, jnp.float32) for s in range(nproc)])
    a2a_out = hvd.alltoall(a2a_in)
    expected_a2a = np.concatenate(
        [np.full((2,), 10.0 * s + me, np.float32) for s in range(nproc)])
    np.testing.assert_allclose(np.asarray(a2a_out), expected_a2a)

    # 3. One real training step: params broadcast from process 0, each
    # process feeds its own data shard, fused-psum DistributedOptimizer.
    params = {"w": jnp.full((3, 2), 0.1 * (me + 1)),
              "b": jnp.zeros((2,))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    opt_state = opt.init(params)

    def step(p, s, bx, by):
        def loss_fn(p):
            return jnp.mean((bx @ p["w"] + p["b"] - by) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, hvd.allreduce(loss)

    fn = hvd.spmd_fn(step, in_specs=(P(), P(), P("hvd"), P("hvd")),
                     out_specs=(P(), P(), P()))
    rng = np.random.RandomState(100 + me)  # DIFFERENT data per process
    bx = jnp.asarray(rng.randn(4, 3), jnp.float32)
    by = jnp.asarray(rng.randn(4, 2), jnp.float32)
    loss0 = None
    for _ in range(5):
        params, opt_state, loss = fn(params, opt_state, bx, by)
        loss0 = float(loss) if loss0 is None else loss0
    assert float(loss) < loss0, (float(loss), loss0)

    # 4. ZeRO-1 across processes — the documented multi-host recipe:
    # global arrays + host_local=False, optimizer state physically
    # sharded over ALL chips of BOTH processes.
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding

    from horovod_tpu.jax import zero

    mesh = hvd.mesh()
    zopt = hvd.sharded_distributed_optimizer(optax.adam(0.05))
    zparams = hvd.broadcast_parameters(
        {"w": jnp.full((3, 2), 0.3), "b": jnp.zeros((2,))}, 0)
    zspec = zero.state_partition_specs(zopt.init(zparams))
    gp = multihost_utils.host_local_array_to_global_array(
        zparams, mesh, P())
    # Create the sharded state ON the mesh (out_shardings from the spec
    # tree): each chip materializes only its slice.
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), zspec,
        is_leaf=lambda x: isinstance(x, P))
    gs = jax.jit(zopt.init, out_shardings=shardings)(gp)

    def zstep(p, s, bx, by):
        def loss_fn(p):
            return jnp.mean((bx @ p["w"] + p["b"] - by) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = zopt.update(g, s, p)
        return optax.apply_updates(p, u), s, hvd.allreduce(loss)

    zfn = hvd.spmd_fn(zstep, in_specs=(P(), zspec, P("hvd"), P("hvd")),
                      out_specs=(P(), zspec, P()), host_local=False)
    gbx = multihost_utils.host_local_array_to_global_array(bx, mesh, P("hvd"))
    gby = multihost_utils.host_local_array_to_global_array(by, mesh, P("hvd"))
    zloss0 = None
    for _ in range(5):
        gp, gs, zloss = zfn(gp, gs, gbx, gby)
        zloss0 = float(zloss) if zloss0 is None else zloss0
    assert float(zloss) < zloss0, (float(zloss), zloss0)
    # The adam moment vectors really live sharded across all 4 chips.
    sharded = [l for l in jax.tree_util.tree_leaves(gs)
               if getattr(l, "ndim", 0) == 1
               and not l.sharding.is_fully_replicated]
    assert sharded, "no sharded optimizer vectors"
    for leaf in sharded:
        assert len(leaf.sharding.device_set) == hvd.size()
        for s in leaf.addressable_shards:
            assert s.data.shape == (leaf.shape[0] // hvd.size(),)

    # 5. Ring attention ACROSS the process boundary: the sequence axis
    # spans every chip of both processes, so the K/V blocks ppermute
    # through cross-process collectives — the distributed long-context
    # path end to end (on real pods this hop is ICI/DCN; here Gloo).
    # Exactness vs locally-computed dense attention, causal mask included.
    import horovod_tpu.parallel as par

    B, L, H, D = 2, 16, 2, 8
    n_chips = hvd.size()
    rng_sp = np.random.RandomState(7)  # identical on every process
    q = rng_sp.randn(B, L, H, D).astype(np.float32)
    k = rng_sp.randn(B, L, H, D).astype(np.float32)
    v = rng_sp.randn(B, L, H, D).astype(np.float32)

    lo, hi = me * (L // nproc), (me + 1) * (L // nproc)  # this host's rows
    ring_local = hvd.spmd_run(
        lambda a, b, c: par.ring_attention(a, b, c, axis="hvd", causal=True),
        jnp.asarray(q[:, lo:hi]), jnp.asarray(k[:, lo:hi]),
        jnp.asarray(v[:, lo:hi]),
        in_specs=(P(None, "hvd"),) * 3, out_specs=P(None, "hvd"),
    )

    # Dense causal reference on the full sequence (same on every host).
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    mask = np.tril(np.ones((L, L), bool))
    s = np.where(mask[None, None], s, -1e30)
    p_att = np.exp(s - s.max(-1, keepdims=True))
    p_att /= p_att.sum(-1, keepdims=True)
    dense = np.einsum("bhqk,bkhd->bqhd", p_att, v)
    np.testing.assert_allclose(np.asarray(ring_local), dense[:, lo:hi],
                               rtol=2e-4, atol=2e-5)
    assert n_chips == _LOCAL * nproc  # the axis really spanned all hosts

    # 5b. Ulysses across the same boundary: TWO n_chips-way alltoalls
    # (sequence->heads, heads->sequence) through the cross-process
    # transport — the np=8 lane's 8-way split exercises source/target
    # orderings a 2- or 4-way exchange cannot distinguish from their
    # inverses. Heads == chips is the minimal legal split; exactness vs
    # the same dense reference restricted to this host's rows.
    Hu = n_chips
    qs = rng_sp.randn(B, L, Hu, D).astype(np.float32)
    ks = rng_sp.randn(B, L, Hu, D).astype(np.float32)
    vs = rng_sp.randn(B, L, Hu, D).astype(np.float32)
    ulys_local = hvd.spmd_run(
        lambda a, b, c: par.ulysses_attention(a, b, c, axis="hvd",
                                              causal=True),
        jnp.asarray(qs[:, lo:hi]), jnp.asarray(ks[:, lo:hi]),
        jnp.asarray(vs[:, lo:hi]),
        in_specs=(P(None, "hvd"),) * 3, out_specs=P(None, "hvd"),
    )
    su = np.einsum("bqhd,bkhd->bhqk", qs, ks) / np.sqrt(D)
    su = np.where(mask[None, None], su, -1e30)
    pu = np.exp(su - su.max(-1, keepdims=True))
    pu /= pu.sum(-1, keepdims=True)
    dense_u = np.einsum("bhqk,bkhd->bqhd", pu, vs)
    np.testing.assert_allclose(np.asarray(ulys_local), dense_u[:, lo:hi],
                               rtol=2e-4, atol=2e-5)

    # Params must be IDENTICAL across processes (same broadcast start,
    # same averaged gradients) — the driver compares the digests.
    flat = np.concatenate([np.asarray(v).ravel()
                           for _, v in sorted(params.items())])
    zflat = np.concatenate([np.asarray(v).ravel()
                            for _, v in sorted(gp.items())])
    digest = hashlib.sha256(flat.tobytes() + zflat.tobytes()).hexdigest()[:16]
    print(f"RESULT rank={me} digest={digest} loss={float(loss):.6f}",
          flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
