"""Orbax CheckpointManager: round-trip of replicated AND sharded
(ZeRO) train state with shardings preserved, step bookkeeping, and GC.

This is the checkpoint path the reference's rank-0 + rebroadcast
discipline cannot cover (sharded state larger than one host); the
msgpack save_model/load_model parity path is tested in
test_flax_callbacks.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.flax as hvd_flax
import horovod_tpu.jax as hvd
from horovod_tpu import models


def _trained_zero_state(hvd, n_steps=2):
    """Train a ZeRO model a couple of steps so the returned state carries
    real (and physically sharded) values."""
    n = hvd.size()
    model = models.MNISTNet()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    state, optimizer = models.create_train_state(
        rng, model, optax.adam(1e-3), sample, zero=True
    )
    step = models.make_train_step(model, optimizer)
    spec = models.state_partition_specs(state)
    fn = hvd.spmd_fn(step, in_specs=(spec, P("hvd")), out_specs=(spec, P()))
    batch = {
        "image": jax.random.normal(rng, (2 * n, 28, 28, 1), jnp.float32),
        "label": jax.random.randint(rng, (2 * n,), 0, 10),
    }
    for _ in range(n_steps):
        state, _ = fn(state, batch)
    return state, fn, batch


def _assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a,
        b,
    )


class TestCheckpointManager:
    def test_sharded_state_round_trip(self, hvd, tmp_path):
        state, fn, batch = _trained_zero_state(hvd)
        with hvd_flax.CheckpointManager(str(tmp_path / "ckpt"),
                                        async_save=False) as ckpt:
            assert ckpt.latest_step() is None
            ckpt.save(2, state)
            assert ckpt.latest_step() == 2
            restored = ckpt.restore(2, template=state)

        _assert_tree_equal(state, restored)
        # Sharded optimizer vectors come back SHARDED, not gathered.
        orig = [l for l in jax.tree_util.tree_leaves(state)
                if getattr(l, "ndim", 0) == 1 and not l.sharding.is_fully_replicated]
        rest = [l for l in jax.tree_util.tree_leaves(restored)
                if getattr(l, "ndim", 0) == 1 and not l.sharding.is_fully_replicated]
        assert orig and len(orig) == len(rest)
        for o, r in zip(orig, rest):
            assert {s.data.shape for s in o.addressable_shards} == \
                   {s.data.shape for s in r.addressable_shards}

        # Resume: the restored state trains on.
        state2, _ = fn(restored, batch)
        assert int(state2["step"]) == int(state["step"]) + 1

    def test_latest_and_gc(self, hvd, tmp_path):
        state, _, _ = _trained_zero_state(hvd, n_steps=1)
        with hvd_flax.CheckpointManager(str(tmp_path / "ckpt"),
                                        max_to_keep=2,
                                        async_save=False) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, state)
            ckpt.wait_until_finished()
            assert ckpt.latest_step() == 3
            assert ckpt.all_steps() == [2, 3]  # step 1 garbage-collected

    def test_checkpoint_callback_in_train_loop(self, hvd, tmp_path):
        """TrainLoop + CheckpointCallback saves on schedule and the saved
        state resumes bit-identically."""
        state, fn, batch = _trained_zero_state(hvd, n_steps=0)

        def data_fn(epoch):
            yield batch

        with hvd_flax.CheckpointManager(str(tmp_path / "cb"),
                                        async_save=False) as mngr:
            loop = hvd_flax.TrainLoop(
                state, fn, data_fn,
                callbacks=[hvd_flax.CheckpointCallback(
                    mngr, every_epochs=2,
                    step_counter=lambda s: int(s["step"]))],
            )
            loop.fit(epochs=4)
            # Saved after epochs 2 and 4 -> train steps 2 and 4.
            assert mngr.all_steps() == [2, 4]
            restored = mngr.restore(template=loop.state)
        _assert_tree_equal(loop.state, restored)

    def test_restore_missing_raises(self, hvd, tmp_path):
        with hvd_flax.CheckpointManager(str(tmp_path / "empty"),
                                        async_save=False) as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()
