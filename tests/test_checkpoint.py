"""Orbax CheckpointManager: round-trip of replicated AND sharded
(ZeRO) train state with shardings preserved, step bookkeeping, and GC.

This is the checkpoint path the reference's rank-0 + rebroadcast
discipline cannot cover (sharded state larger than one host); the
msgpack save_model/load_model parity path is tested in
test_flax_callbacks.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.flax as hvd_flax
import horovod_tpu.jax as hvd
from horovod_tpu import models


def _trained_zero_state(hvd, n_steps=2):
    """Train a ZeRO model a couple of steps so the returned state carries
    real (and physically sharded) values."""
    n = hvd.size()
    model = models.MNISTNet()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    state, optimizer = models.create_train_state(
        rng, model, optax.adam(1e-3), sample, zero=True
    )
    step = models.make_train_step(model, optimizer)
    spec = models.state_partition_specs(state)
    fn = hvd.spmd_fn(step, in_specs=(spec, P("hvd")), out_specs=(spec, P()))
    batch = {
        "image": jax.random.normal(rng, (2 * n, 28, 28, 1), jnp.float32),
        "label": jax.random.randint(rng, (2 * n,), 0, 10),
    }
    for _ in range(n_steps):
        state, _ = fn(state, batch)
    return state, fn, batch


def _assert_tree_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a,
        b,
    )


class TestCheckpointManager:
    def test_sharded_state_round_trip(self, hvd, tmp_path):
        state, fn, batch = _trained_zero_state(hvd)
        with hvd_flax.CheckpointManager(str(tmp_path / "ckpt"),
                                        async_save=False) as ckpt:
            assert ckpt.latest_step() is None
            ckpt.save(2, state)
            assert ckpt.latest_step() == 2
            restored = ckpt.restore(2, template=state)

        _assert_tree_equal(state, restored)
        # Sharded optimizer vectors come back SHARDED, not gathered.
        orig = [l for l in jax.tree_util.tree_leaves(state)
                if getattr(l, "ndim", 0) == 1 and not l.sharding.is_fully_replicated]
        rest = [l for l in jax.tree_util.tree_leaves(restored)
                if getattr(l, "ndim", 0) == 1 and not l.sharding.is_fully_replicated]
        assert orig and len(orig) == len(rest)
        for o, r in zip(orig, rest):
            assert {s.data.shape for s in o.addressable_shards} == \
                   {s.data.shape for s in r.addressable_shards}

        # Resume: the restored state trains on.
        state2, _ = fn(restored, batch)
        assert int(state2["step"]) == int(state["step"]) + 1

    def test_latest_and_gc(self, hvd, tmp_path):
        state, _, _ = _trained_zero_state(hvd, n_steps=1)
        with hvd_flax.CheckpointManager(str(tmp_path / "ckpt"),
                                        max_to_keep=2,
                                        async_save=False) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, state)
            ckpt.wait_until_finished()
            assert ckpt.latest_step() == 3
            assert ckpt.all_steps() == [2, 3]  # step 1 garbage-collected

    def test_checkpoint_callback_in_train_loop(self, hvd, tmp_path):
        """TrainLoop + CheckpointCallback saves on schedule and the saved
        state resumes bit-identically."""
        state, fn, batch = _trained_zero_state(hvd, n_steps=0)

        def data_fn(epoch):
            yield batch

        with hvd_flax.CheckpointManager(str(tmp_path / "cb"),
                                        async_save=False) as mngr:
            loop = hvd_flax.TrainLoop(
                state, fn, data_fn,
                callbacks=[hvd_flax.CheckpointCallback(
                    mngr, every_epochs=2,
                    step_counter=lambda s: int(s["step"]))],
            )
            loop.fit(epochs=4)
            # Saved after epochs 2 and 4 -> train steps 2 and 4.
            assert mngr.all_steps() == [2, 4]
            restored = mngr.restore(template=loop.state)
        _assert_tree_equal(loop.state, restored)

    def test_restore_missing_raises(self, hvd, tmp_path):
        with hvd_flax.CheckpointManager(str(tmp_path / "empty"),
                                        async_save=False) as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()


# --------------------------------------------------------------- backends
# Direct CheckpointManager coverage on BOTH backends (ISSUE satellite):
# the orbax path and the pure-numpy per-process shard writer that the
# elastic disk spill uses in environments without orbax.


@pytest.fixture(params=["numpy", "orbax"])
def backend(request):
    if request.param == "orbax":
        pytest.importorskip("orbax.checkpoint")
    return request.param


class TestCheckpointBackends:
    def _state(self, hvd):
        return {
            "w": jnp.arange(12.0, dtype=jnp.float32).reshape(3, 4),
            "b": jnp.ones((4,), jnp.bfloat16),
            "step": jnp.asarray(7, jnp.int32),
        }

    def test_backend_resolution(self, tmp_path, backend):
        with hvd_flax.CheckpointManager(str(tmp_path), backend=backend,
                                        async_save=False) as ckpt:
            assert ckpt.backend == backend

    def test_save_restore_latest(self, hvd, tmp_path, backend):
        state = self._state(hvd)
        with hvd_flax.CheckpointManager(str(tmp_path), backend=backend,
                                        async_save=False) as ckpt:
            assert ckpt.latest_step() is None
            assert ckpt.save(5, state)
            assert ckpt.latest_step() == 5
            restored = ckpt.restore(5, template=state)
        _assert_tree_equal(state, restored)
        # bfloat16 round-trips bit-exactly (the numpy backend stores raw
        # bytes + dtype name, not a lossy cast).
        assert jax.tree_util.tree_leaves(restored)[0].dtype == \
            jnp.bfloat16

    def test_latest_and_gc(self, hvd, tmp_path, backend):
        state = self._state(hvd)
        with hvd_flax.CheckpointManager(str(tmp_path), max_to_keep=2,
                                        backend=backend,
                                        async_save=False) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, state)
            ckpt.wait_until_finished()
            assert ckpt.latest_step() == 3
            assert ckpt.all_steps() == [2, 3]

    def test_restore_default_step_is_latest(self, hvd, tmp_path, backend):
        state = self._state(hvd)
        with hvd_flax.CheckpointManager(str(tmp_path), backend=backend,
                                        async_save=False) as ckpt:
            ckpt.save(1, state)
            ckpt.save(4, jax.tree_util.tree_map(lambda x: x * 2, state))
            restored = ckpt.restore(template=state)
        _assert_tree_equal(
            restored, jax.tree_util.tree_map(lambda x: x * 2, state))

    def test_sharded_leaves_round_trip(self, hvd, tmp_path, backend):
        """Locally-sharded leaves (the single-host ZeRO shape) come back
        with their sharding on both backends."""
        from jax.sharding import NamedSharding

        mesh = hvd.mesh()
        sharding = NamedSharding(mesh, P("hvd"))
        vec = jax.device_put(jnp.arange(16.0), sharding)
        state = {"sharded": vec, "replicated": jnp.ones((3,))}
        with hvd_flax.CheckpointManager(str(tmp_path), backend=backend,
                                        async_save=False) as ckpt:
            ckpt.save(1, state)
            restored = ckpt.restore(1, template=state)
        _assert_tree_equal(state, restored)
        assert not restored["sharded"].sharding.is_fully_replicated
        assert {s.data.shape for s in
                restored["sharded"].addressable_shards} == \
               {s.data.shape for s in vec.addressable_shards}


class TestNumpyBackendContracts:
    """Failure-mode contracts specific to the fallback writer."""

    def test_template_required(self, tmp_path):
        state = {"w": jnp.ones((2,))}
        with hvd_flax.CheckpointManager(str(tmp_path), backend="numpy",
                                        async_save=False) as ckpt:
            ckpt.save(1, state)
            with pytest.raises(ValueError, match="template"):
                ckpt.restore(1)

    def test_uncommitted_step_invisible(self, tmp_path):
        """Atomic rename-commit: a step dir without the COMMIT marker (a
        writer died mid-save) is ignored by latest_step/all_steps and
        restore."""
        state = {"w": jnp.ones((2,))}
        with hvd_flax.CheckpointManager(str(tmp_path), backend="numpy",
                                        async_save=False) as ckpt:
            ckpt.save(1, state)
            (tmp_path / "step_2").mkdir()  # torn save: shards, no COMMIT
            (tmp_path / "step_2" / "shard-0.bin").write_bytes(b"junk")
            assert ckpt.all_steps() == [1]
            assert ckpt.latest_step() == 1
            with pytest.raises(FileNotFoundError):
                ckpt.restore(2, template=state)

    def test_structure_mismatch_rejected(self, tmp_path):
        with hvd_flax.CheckpointManager(str(tmp_path), backend="numpy",
                                        async_save=False) as ckpt:
            ckpt.save(1, {"w": jnp.ones((2,))})
            with pytest.raises(ValueError, match="leaves"):
                ckpt.restore(1, template={"w": jnp.ones((2,)),
                                          "extra": jnp.ones((1,))})

    def test_forced_backend_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVD_CHECKPOINT_BACKEND", "numpy")
        with hvd_flax.CheckpointManager(str(tmp_path)) as ckpt:
            assert ckpt.backend == "numpy"

    def test_bad_backend_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="backend"):
            hvd_flax.CheckpointManager(str(tmp_path), backend="msgpack")
