"""HOROVOD_TIMELINE on the flagship SPMD lane.

Parity with reference test/test_timeline.py:42-58: run real ops with the
env var set, then assert on the Chrome-trace JSON content. Round-1 gap:
the SPMD lane defined XLA_* activity names but never emitted them, so a
training run produced an empty trace.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import horovod_tpu.jax as hvd

hvd.init()

def step(x):
    return hvd.allreduce(x, name="tl_grad")

run = hvd.spmd_fn(step, in_specs=P("hvd"), out_specs=P("hvd"))
x = jnp.ones((8, 4), jnp.float32)
for _ in range(3):
    out = run(x)
jax.block_until_ready(out)

# Bucketed gradient reduce: the fusion layer emits per-bucket
# ALLREDUCE + MEMCPY_IN/OUT_FUSION_BUFFER spans at trace time.
from horovod_tpu.jax.fusion import fused_reduce

def grad_step(a, b):
    ra, rb = fused_reduce([a, b], average=True, name="grads")
    return ra, rb

grun = hvd.spmd_fn(grad_step, in_specs=(P("hvd"), P("hvd")),
                   out_specs=(P("hvd"), P("hvd")))
ga, gb = grun(x, x * 2)
jax.block_until_ready(ga)
hvd.shutdown()
print("DONE")
"""


def test_spmd_timeline_content(tmp_path):
    trace = tmp_path / "timeline.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_TIMELINE"] = str(trace)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT], env=env, cwd=str(REPO),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "DONE" in proc.stdout

    text = trace.read_text()
    events = json.loads(text.rstrip().rstrip(",\n") + "]")
    names = [e.get("name") for e in events]
    # First dispatch = trace+compile; later dispatches = execute.
    assert "XLA_COMPILE" in names
    assert "XLA_EXECUTE" in names
    # B/E nesting per activity, and the step track is labeled.
    phases = {e.get("ph") for e in events}
    assert {"B", "E", "M"} <= phases
    tracks = [e["args"]["name"] for e in events
              if e.get("name") == "thread_name"]
    assert "step" in tracks
    compile_b = [e for e in events
                 if e.get("name") == "XLA_COMPILE" and e["ph"] == "B"]
    execute_b = [e for e in events
                 if e.get("name") == "XLA_EXECUTE" and e["ph"] == "B"]
    # 2 handles -> 2 compiles; step ran 3x (1 compile + 2 executes).
    assert len(compile_b) == 2
    assert len(execute_b) == 2

    # Per-bucket granularity (VERDICT r4 #8): the named gradient bucket
    # gets an ALLREDUCE activity on its own track — reference activity
    # taxonomy (operations.h:29-50), not just XLA_EXECUTE.
    assert "grads.float32.b0" in tracks
    bucket_tid = next(e["tid"] for e in events
                      if e.get("name") == "thread_name"
                      and e["args"]["name"] == "grads.float32.b0")
    bucket_names = {e.get("name") for e in events
                    if e.get("tid") == bucket_tid and e.get("ph") == "B"}
    assert "ALLREDUCE" in bucket_names
    assert "MEMCPY_IN_FUSION_BUFFER" in bucket_names
    assert "MEMCPY_OUT_FUSION_BUFFER" in bucket_names
    ar = next(e for e in events if e.get("name") == "ALLREDUCE"
              and e.get("tid") == bucket_tid and e["ph"] == "B")
    assert ar["args"]["tensors"] == 2
    assert ar["args"]["span"] == "trace"
