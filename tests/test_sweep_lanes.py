"""Static preflight of every tools/hw_sweep.py lane: arg wiring, model
registry membership, and flag applicability — so a wiring bug can never
again cost a hardware window (round 3 lost one to an import-path bug the
CPU suite had no coverage for; these checks run in milliseconds)."""

import importlib.util
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def lanes():
    return _load("hw_sweep", REPO / "tools" / "hw_sweep.py").LANES


@pytest.fixture(scope="module")
def parser():
    return _load("bench_mod", REPO / "bench.py").build_parser()


def test_every_bench_lane_parses(lanes, parser):
    for entry in lanes:
        lane, cmd = entry[0], entry[1]
        if cmd[0] != "bench.py":
            continue
        args = parser.parse_args(cmd[1:])
        assert args is not None, lane


def test_every_lane_model_exists(lanes, parser):
    from horovod_tpu import models

    for entry in lanes:
        lane, cmd = entry[0], entry[1]
        if cmd[0] != "bench.py":
            continue
        args = parser.parse_args(cmd[1:])
        if args.model == "transformer_lm":
            continue  # bench_lm builds its own model
        # models.build raises for unknown names; num_classes keeps the
        # constructor cheap (no params materialized at build time).
        models.build(args.model, num_classes=10)


def test_every_lane_script_exists(lanes):
    for entry in lanes:
        cmd = entry[1]
        assert (REPO / cmd[0]).exists(), cmd[0]


def test_image_only_flags_not_on_lm_lanes(lanes, parser):
    """bench_image rejects LM flags and vice versa at runtime; catch a
    mis-assembled lane here instead of on the chip."""
    for entry in lanes:
        lane, cmd = entry[0], entry[1]
        if cmd[0] != "bench.py":
            continue
        args = parser.parse_args(cmd[1:])
        lm_flags = (args.fused_ce or args.scan_layers or args.remat
                    or args.flash_attention or args.flash_full_grid
                    or args.attention is not None
                    or args.flash_bwd is not None)
        if args.model != "transformer_lm":
            assert not lm_flags, f"{lane}: LM flag on an image lane"
        if args.model == "transformer_lm":
            assert not args.fused_bn, f"{lane}: --fused-bn on the LM lane"
        if args.flash_full_grid:
            # The full-grid A/B lane only means something on the flash
            # path; bench_lm rejects the combination at runtime.
            assert (args.flash_attention or args.attention == "flash"), \
                f"{lane}: --flash-full-grid without the flash path"


def test_serve_tp_lane_geometry_divides(lanes):
    """The serve_tp_ab lane must not fail-fast on the chip: the tp
    degree it requests has to divide the default model geometry
    (heads, mlp = 4*d_model, vocab — tools/lm_common.py defaults),
    because ServeEngine raises InvalidArgumentError at construction
    otherwise. A mis-paired lane edit dies here in milliseconds."""
    entry = next(e for e in lanes if e[0] == "serve_tp_ab")
    cmd = entry[1]
    assert cmd[0] == "tools/serve_bench.py"
    assert "--ab-tp" in cmd and "--mesh" in cmd
    mesh = cmd[cmd.index("--mesh") + 1]
    axes = dict(kv.split("=") for kv in mesh.split(","))
    tp = int(axes["tp"])
    assert tp > 1, "the A/B needs a sharded side"
    heads, d_model, vocab = 12, 768, 32000  # lm_common defaults
    assert heads % tp == 0
    assert (4 * d_model) % tp == 0
    assert vocab % tp == 0
    # every non-tensor axis must be 1 (data parallelism is the
    # fleet's job — ServeConfig rejects dp>1)
    assert all(int(v) == 1 for k, v in axes.items() if k != "tp")


def test_parser_builds_without_backend_init(parser):
    """build_parser must not initialize a backend (the sweep imports it
    on a box whose tunnel may be wedged): bench.py defers its jax import
    into the bench functions, so building + using the parser alone must
    succeed with defaults intact."""
    args = parser.parse_args([])
    assert args.model == "resnet50" and args.seq_len == 2048
