"""ZeRO-1 sharded optimizer: equivalence with the flat DistributedOptimizer
path, physical sharding of the state, padding edge cases, and the
end-to-end ``create_train_state(zero=True)`` story.

The reference has no ZeRO (it predates it); the correctness oracle is the
repo's own flat lane — reduce-scatter + shard-update + all-gather must give
bit-compatible results with allreduce + replicated-update, because that is
literally the same ring decomposed (see horovod_tpu/jax/zero.py docstring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvd
from horovod_tpu.jax import zero
from horovod_tpu.jax.optimizer import DistributedOptimizer


def _params():
    k = jax.random.PRNGKey(0)
    return {
        "w1": jax.random.normal(k, (13, 7), jnp.float32),
        "b1": jnp.zeros((7,), jnp.float32),
        "w2": jax.random.normal(jax.random.fold_in(k, 1), (7, 3), jnp.float32),
    }


def _per_rank_grads(n):
    """(n, ...)-leading stack of per-rank gradient pytrees."""
    k = jax.random.PRNGKey(42)
    p = _params()
    return {
        name: jax.random.normal(jax.random.fold_in(k, i), (n,) + leaf.shape, leaf.dtype)
        for i, (name, leaf) in enumerate(sorted(p.items()))
    }


def _run_steps(optimizer, opt_specs, params, grads_stack, n_steps=3):
    """Run ``n_steps`` updates under SPMD; grads arrive sharded by rank."""
    opt_state = optimizer.init(params)

    def step(params, opt_state, g):
        g = jax.tree_util.tree_map(lambda t: t[0], g)  # drop the rank dim
        updates, opt_state = optimizer.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    fn = hvd.spmd_fn(
        step,
        in_specs=(P(), opt_specs, P("hvd")),
        out_specs=(P(), opt_specs),
    )
    for _ in range(n_steps):
        params, opt_state = fn(params, opt_state, grads_stack)
    return params, opt_state


class TestZeroEquivalence:
    def test_adam_matches_flat(self, hvd):
        n = hvd.size()
        params = _params()
        grads = _per_rank_grads(n)

        flat_opt = DistributedOptimizer(optax.adam(1e-2))
        p_flat, _ = _run_steps(flat_opt, P(), params, grads)

        z_opt = hvd.sharded_distributed_optimizer(optax.adam(1e-2))
        z_specs = zero.state_partition_specs(z_opt.init(params))
        p_zero, _ = _run_steps(z_opt, z_specs, params, grads)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
            ),
            p_flat,
            p_zero,
        )

    def test_adamw_params_dependent_matches_flat(self, hvd):
        """adamw reads params (weight decay): exercises the param-shard
        slice path."""
        n = hvd.size()
        params = _params()
        grads = _per_rank_grads(n)

        flat_opt = DistributedOptimizer(optax.adamw(1e-2, weight_decay=0.1))
        p_flat, _ = _run_steps(flat_opt, P(), params, grads)

        z_opt = hvd.sharded_distributed_optimizer(
            optax.adamw(1e-2, weight_decay=0.1)
        )
        z_specs = zero.state_partition_specs(z_opt.init(params))
        p_zero, _ = _run_steps(z_opt, z_specs, params, grads)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
            ),
            p_flat,
            p_zero,
        )

    def test_momentum_non_divisible_total(self, hvd):
        """Total param count (13*7 + 7 + 7*3 = 119) is not divisible by 8:
        the padded tail must not perturb results."""
        n = hvd.size()
        assert (13 * 7 + 7 + 7 * 3) % n != 0
        params = _params()
        grads = _per_rank_grads(n)

        flat_opt = DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
        p_flat, _ = _run_steps(flat_opt, P(), params, grads)

        z_opt = hvd.sharded_distributed_optimizer(optax.sgd(0.1, momentum=0.9))
        z_specs = zero.state_partition_specs(z_opt.init(params))
        p_zero, _ = _run_steps(z_opt, z_specs, params, grads)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            ),
            p_flat,
            p_zero,
        )


class TestZeroSharding:
    def test_state_physically_sharded(self, hvd):
        """After a step, the momentum vectors live sharded over the mesh:
        each device holds pad/n elements, not the whole vector."""
        n = hvd.size()
        params = _params()
        z_opt = hvd.sharded_distributed_optimizer(optax.adam(1e-2))
        state0 = z_opt.init(params)
        info = zero.shard_info(state0)
        (pad, per_rank) = info["float32"]
        total = sum(l.size for l in jax.tree_util.tree_leaves(params))
        assert pad == ((total + n - 1) // n) * n
        assert per_rank * n == pad

        specs = zero.state_partition_specs(state0)
        _, state1 = _run_steps(z_opt, specs, params, _per_rank_grads(n), n_steps=1)

        sharded_leaves = [
            l
            for l in jax.tree_util.tree_leaves(state1)
            if getattr(l, "ndim", 0) == 1 and l.shape[0] == pad
        ]
        assert sharded_leaves, "no sharded momentum vectors found"
        for leaf in sharded_leaves:
            shard_shapes = {s.data.shape for s in leaf.addressable_shards}
            assert shard_shapes == {(per_rank,)}, (
                f"state leaf not sharded: {shard_shapes}"
            )

    def test_spec_tree_marks_only_flat_vectors(self, hvd):
        params = _params()
        z_opt = hvd.sharded_distributed_optimizer(optax.adam(1e-2))
        state = z_opt.init(params)
        specs = zero.state_partition_specs(state)
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        # adam: count (replicated) + mu + nu (sharded)
        assert spec_leaves.count(P("hvd")) == 2
        assert spec_leaves.count(P()) == 1

    def test_single_rank_degrades_to_plain_optimizer(self, hvd):
        """Outside SPMD with one process, zero == the unwrapped optimizer."""
        params = _params()
        g = jax.tree_util.tree_map(jnp.ones_like, params)

        plain = optax.adam(1e-2)
        ps = plain.init(params)
        u_plain, _ = plain.update(g, ps, params)

        z = hvd.sharded_distributed_optimizer(optax.adam(1e-2))
        zs = z.init(params)
        u_zero, _ = z.update(g, zs, params)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            u_plain,
            u_zero,
        )

    def test_fp16_compressed_wire(self, hvd):
        """Compression applies to the reduce-scatter wire: results stay
        within fp16 quantization of the uncompressed path."""
        n = hvd.size()
        params = _params()
        grads = _per_rank_grads(n)

        exact = hvd.sharded_distributed_optimizer(optax.sgd(0.1))
        specs = zero.state_partition_specs(exact.init(params))
        p_exact, _ = _run_steps(exact, specs, params, grads, n_steps=1)

        from horovod_tpu.jax.compression import Compression

        comp = hvd.sharded_distributed_optimizer(
            optax.sgd(0.1), compression=Compression.fp16
        )
        p_comp, _ = _run_steps(comp, specs, params, grads, n_steps=1)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=5e-3
            ),
            p_exact,
            p_comp,
        )

    def test_global_norm_clip_composes_outside(self, hvd):
        """The documented recipe for non-elementwise transforms: compose
        them OUTSIDE the zero wrapper (they see full gradients there).
        chain(clip_by_global_norm, zero(sgd)) must equal the flat path."""
        n = hvd.size()
        params = _params()
        grads = _per_rank_grads(n)

        flat_opt = DistributedOptimizer(
            optax.chain(optax.clip_by_global_norm(0.05), optax.sgd(0.1)))
        p_flat, _ = _run_steps(flat_opt, P(), params, grads, n_steps=2)

        # Average + clip on the FULL gradient, then shard the update.
        # The inner reduce-scatter averages ALREADY-IDENTICAL grads
        # (its default average=True makes it an identity reduction here).
        z_inner = hvd.sharded_distributed_optimizer(optax.sgd(0.1))
        z_opt = optax.chain(
            hvd.allreduce_gradients_transform(),
            optax.clip_by_global_norm(0.05),
            z_inner,
        )
        z_specs = zero.state_partition_specs(z_opt.init(params))
        p_zero, _ = _run_steps(z_opt, z_specs, params, grads, n_steps=2)

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-7
            ),
            p_flat,
            p_zero,
        )

    def test_dtype_mismatch_rejected(self, hvd):
        params = _params()
        z = hvd.sharded_distributed_optimizer(optax.sgd(0.1))
        zs = z.init(params)
        bad = jax.tree_util.tree_map(
            lambda t: t.astype(jnp.bfloat16), params
        )
        with pytest.raises(ValueError, match="dtypes"):
            z.update(bad, zs, params)


class TestZeroTrainState:
    def test_create_train_state_zero_end_to_end(self, hvd):
        """Full story: create_train_state(zero=True) + make_train_step +
        state_partition_specs trains and the loss is finite."""
        from horovod_tpu import models

        n = hvd.size()
        model = models.MNISTNet()
        rng = jax.random.PRNGKey(0)
        sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
        state, optimizer = models.create_train_state(
            rng, model, optax.adam(1e-3), sample, zero=True
        )
        step = models.make_train_step(model, optimizer)
        spec = models.state_partition_specs(state)

        batch = {
            "image": jax.random.normal(rng, (2 * n, 28, 28, 1), jnp.float32),
            "label": jax.random.randint(rng, (2 * n,), 0, 10),
        }
        fn = hvd.spmd_fn(
            step, in_specs=(spec, P("hvd")), out_specs=(spec, P())
        )
        state, metrics = fn(state, batch)
        state, metrics = fn(state, batch)
        assert int(state["step"]) == 2
        assert np.isfinite(float(metrics["loss"]))

    def test_zero_vs_flat_training_equivalence(self, hvd):
        """The same model trained 3 steps with flat DP vs ZeRO lands on the
        same weights."""
        from horovod_tpu import models

        n = hvd.size()
        rng = jax.random.PRNGKey(7)
        sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
        batch = {
            "image": jax.random.normal(rng, (2 * n, 28, 28, 1), jnp.float32),
            "label": jax.random.randint(rng, (2 * n,), 0, 10),
        }

        def train(zero_flag):
            model = models.MNISTNet()
            state, optimizer = models.create_train_state(
                jax.random.PRNGKey(0), model, optax.sgd(0.05, momentum=0.9),
                sample, zero=zero_flag,
            )
            step = models.make_train_step(model, optimizer)
            spec = models.state_partition_specs(state) if zero_flag else P()
            fn = hvd.spmd_fn(
                step, in_specs=(spec, P("hvd")), out_specs=(spec, P())
            )
            for _ in range(3):
                state, _ = fn(state, batch)
            return state["params"]

        p_flat = train(False)
        p_zero = train(True)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-5, atol=1e-6
            ),
            p_flat,
            p_zero,
        )
