"""Protocol-faithful serving-fleet stub worker — no jax, ~30 ms start.

The FAST stand-in for the real ``python -m horovod_tpu.serve.worker``
(which pays a multi-second jax import per spawn, so its end-to-end
tests are slow-marked): this stub speaks the exact same framed RPC
protocol (``submit``/``step``/``collect``/``stats``/``drain``/
``reset_metrics``/``fault``/``shutdown``/``ping``), stamps the same
per-tick heartbeat file, honors the same fault and test hooks
(``HVD_SERVE_WORKER_TORN_COLLECT_AFTER``,
``HVD_SERVE_WORKER_FAIL_START``), and is launched with ``python -S``
so it never even imports site-packages — letting the whole
process-fleet recovery matrix (transport death paths, watchdog stalls,
close escalation, startup crashes) run in the fast test lane against
real OS processes.

Its "model" is a deterministic context hash SALTED by the params
artifact the fleet pushed over the wire: the next token depends on the
full context (prompt + everything generated) AND the sha256 of the
worker's current weights, exactly like greedy LM decoding — so a
redispatch that folds the generated-so-far prefix into the prompt
(``rebase_for_recompute``) continues the identical stream, the
at-most-once/bit-exact pins hold for the same reason they hold on the
real engine, and a PARAMS VERSION change observably changes the
stream (which is what makes the rolling-update pins — no mixed-version
stream, wire-init actually delivered the weights — provable without
jax).

Loaded as a module by tests for :func:`expected_stream`; run as a
script by the fleet's ``worker_cmd`` hook.
"""

import argparse
import importlib.util
import os
import sys
import tempfile
import threading
import time

VOCAB = 97


def next_token(context, salt=0):
    h = int(salt) % 1000003
    for t in context:
        h = (h * 31 + int(t) + 1) % 1000003
    return h % VOCAB


def expected_stream(prompt, n, salt=0):
    """The stream an uninterrupted greedy 'decode' of ``prompt`` emits
    under the weights whose digest-derived ``salt`` this is — and,
    because each token depends on the full context, the stream any
    rebased SAME-VERSION redispatch must continue bit-identically."""
    ctx = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        t = next_token(ctx, salt)
        ctx.append(t)
        out.append(t)
    return out


def salt_for_sha(sha_hex):
    """The stub model's weights: the artifact digest, folded small."""
    return int(sha_hex[:8], 16)


def params_salt(params):
    """Test-side twin: the salt a stub serving ``params`` (pushed by
    the fleet as a wire artifact) decodes with."""
    pw = _load_serve_module("params_wire")
    return salt_for_sha(pw.sha256_hex(pw.params_to_blob(params)))


def _load_serve_module(name):
    """Load one horovod_tpu/serve module by FILE, pre-seeding stub
    package entries in sys.modules so intra-package imports (e.g.
    params_wire's ``from horovod_tpu.serve.transport import ...``)
    resolve WITHOUT executing the real package __init__ (which pulls
    the whole serve stack — the stub runs ``python -S`` with no
    site-packages and must stay jax/numpy-free on its hot path)."""
    import types

    here = os.path.dirname(os.path.abspath(__file__))
    serve_dir = os.path.join(os.path.dirname(here), "horovod_tpu",
                             "serve")
    full = f"horovod_tpu.serve.{name}"
    if full in sys.modules:
        return sys.modules[full]
    for pkg in ("horovod_tpu", "horovod_tpu.serve"):
        if pkg not in sys.modules:
            mod = types.ModuleType(pkg)
            mod.__path__ = []
            sys.modules[pkg] = mod
    if name != "transport" \
            and "horovod_tpu.serve.transport" not in sys.modules:
        _load_serve_module("transport")
    if name not in ("transport", "chunk_stream") \
            and "horovod_tpu.serve.chunk_stream" not in sys.modules:
        _load_serve_module("chunk_stream")
    spec = importlib.util.spec_from_file_location(
        full, os.path.join(serve_dir, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[full] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_transport():
    return _load_serve_module("transport")


class StubHost:
    def __init__(self, transport, slots, heartbeat_path, tick_s,
                 secret=None):
        self.T = transport
        self.slots = slots
        self.heartbeat_path = heartbeat_path
        self.tick_s = tick_s
        self._secret = secret
        self._hb = 0       # transport liveness seq (real-worker parity)
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._requests = {}    # rid -> dict(prompt, max_new, output)
        self._order = []       # fcfs admission order
        self._terminal = []
        self._ticks = 0
        self._last_hb = 0.0
        self._stall_pending = None
        self._slow = 1.0
        self._collects = 0
        torn = os.environ.get("HVD_SERVE_WORKER_TORN_COLLECT_AFTER")
        self._torn_after = int(torn) if torn else None
        #: Wire weight distribution (real-worker parity): the fleet
        #: pushes config + a versioned params artifact at spawn and on
        #: rolling updates; the committed artifact's digest salts the
        #: stub's "model" so a version change changes the stream.
        self._salt = 0
        self._version = None
        self._sha = None
        self._config = None
        self._assembler = None
        self._artifact_dir = None
        self._pushes = 0
        die = os.environ.get("HVD_STUB_DIE_ON_PUSH_CHUNK")
        #: test hook: os._exit(1) on the Nth push_chunk — the
        #: kill-mid-push shape (retry consumes budget, then the
        #: replica-death path).
        self._die_on_chunk = int(die) if die else None

    # ------------------------------------------------ engine loop

    def serve_loop(self):
        while not self._shutdown.is_set():
            with self._lock:
                stall, self._stall_pending = self._stall_pending, None
            if stall is not None:
                secs = stall.get("secs")
                if secs is None:
                    while not self._shutdown.is_set():
                        time.sleep(0.2)
                    break
                time.sleep(float(secs))
            t0 = time.monotonic()
            with self._lock:
                progressed = self._tick_locked()
                if progressed:
                    self._ticks += 1
            self._hb += 1
            if progressed and self._slow > 1.0:
                time.sleep((self._slow - 1.0)
                           * max(time.monotonic() - t0, self.tick_s))
            if self.heartbeat_path:
                # same 50 ms rate limit as the real worker
                now = time.monotonic()
                if now - self._last_hb >= 0.05:
                    with open(self.heartbeat_path, "w") as f:
                        f.write(f"{self._ticks}\n")
                    self._last_hb = now
            time.sleep(self.tick_s if progressed else 0.002)

    def _tick_locked(self):
        active = [r for r in self._order
                  if r in self._requests][:self.slots]
        progressed = False
        for rid in active:
            req = self._requests[rid]
            ctx = req["prompt"] + req["output"]
            req["output"].append(next_token(ctx, self._salt))
            progressed = True
            if len(req["output"]) >= req["max_new"]:
                self._terminal.append({
                    "rid": rid, "state": "finished",
                    "output": list(req["output"]),
                    "prefill_pos": len(req["prompt"]),
                    "generated_len": len(req["output"]),
                    "evictions": 0,
                    "reject_reason": None, "retry_after": None,
                })
                del self._requests[rid]
        self._order = [r for r in self._order if r in self._requests]
        return progressed

    # ------------------------------------------------ RPC handlers

    def handle(self, method, params):
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None or not method:
            raise ValueError(f"unknown RPC method {method!r}")
        return fn(params)

    def _rpc_ping(self, p):
        return {"pid": os.getpid(), "ticks": self._ticks,
                "hb": self._hb, "params_version": self._version,
                "params_sha256": self._sha}

    # ------------------------------------------ transfer RPCs

    def _rpc_put_config(self, p):
        cfg = p.get("config")
        if not isinstance(cfg, dict):
            raise ValueError("put_config: expected a config mapping")
        self._config = dict(cfg)
        return {}

    def _rpc_push_begin(self, p):
        pw = _load_serve_module("params_wire")
        if self._artifact_dir is None:
            self._artifact_dir = tempfile.mkdtemp(
                prefix="hvd-stub-params-")
        asm = pw.ArtifactAssembler(self._artifact_dir)
        have = asm.begin(p.get("manifest"))
        self._assembler = asm
        return {"have_bytes": have}

    def _rpc_push_chunk(self, p):
        if self._assembler is None:
            raise ValueError("push_chunk before push_begin")
        self._pushes += 1
        if self._die_on_chunk is not None \
                and self._pushes >= self._die_on_chunk:
            os._exit(1)   # kill-mid-push: the worker-lost-mid-transfer shape
        return {"have_bytes": self._assembler.write_chunk(p)}

    def _rpc_push_commit(self, p):
        asm = self._assembler
        if asm is None:
            raise ValueError("push_commit before push_begin")
        path, sha = asm.commit()
        self._assembler = None
        pw = _load_serve_module("params_wire")
        pw.prune_artifacts(self._artifact_dir, path)
        with self._lock:
            self._version = int(asm.manifest["version"])
            self._sha = sha
            self._salt = salt_for_sha(sha)
        return {"version": self._version, "sha256": sha}

    def _rpc_submit(self, p):
        with self._lock:
            rid = int(p["rid"])
            self._requests[rid] = {
                "prompt": [int(t) for t in p["prompt"]],
                "max_new": int(p["max_new_tokens"]),
                "output": [],
            }
            self._order.append(rid)
            return {"accepted": True}

    def _rpc_step(self, p):
        with self._lock:
            return {"ticks": self._ticks,
                    "hb": self._hb,
                    "free_slots": max(0, self.slots
                                      - len(self._requests)),
                    "occupancy": 0.0,
                    "queue_len": 0,
                    "in_flight": len(self._requests),
                    "idle": not self._requests}

    def _rpc_collect(self, p):
        since = p.get("since") or {}
        with self._lock:
            events, self._terminal = self._terminal, []
            progress = []
            for rid_s, n in since.items():
                req = self._requests.get(int(rid_s))
                if req is None:
                    continue
                progress.append({
                    "rid": int(rid_s),
                    "tokens": req["output"][int(n):],
                    "prefill_pos": len(req["prompt"]),
                    "generated_len": len(req["output"]),
                })
        self._collects += 1
        return {"events": events, "progress": progress,
                "hb": self._hb}

    def _rpc_stats(self, p):
        with self._lock:
            return {"in_flight": len(self._requests),
                    "ticks": self._ticks}

    def _rpc_drain(self, p):
        deadline = time.monotonic() + float(p.get("timeout", 5.0))
        while time.monotonic() < deadline:
            with self._lock:
                if not self._requests:
                    return {"idle": True}
            time.sleep(0.002)
        return {"idle": False}

    def _rpc_reset_metrics(self, p):
        with self._lock:
            self._ticks = 0
        return {"ticks": 0}

    def _rpc_fault(self, p):
        kind = p.get("kind")
        with self._lock:
            if kind == "stall":
                self._stall_pending = {"secs": p.get("secs")}
            elif kind == "slow":
                self._slow = float(p["factor"])
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        return {}

    def _rpc_shutdown(self, p):
        self._shutdown.set()
        timer = threading.Timer(0.5, os._exit, args=(0,))
        timer.daemon = True
        timer.start()
        return {"pid": os.getpid()}

    # ------------------------------------------------ plumbing

    def _send_hook(self, sock, frame):
        if self._torn_after is not None \
                and self._collects >= self._torn_after:
            sock.settimeout(5.0)
            sock.sendall(frame[:max(1, len(frame) // 2)])
            os._exit(1)
        return False

    def rpc_loop(self, server_sock):
        import socket as _socket

        while not self._shutdown.is_set():
            server_sock.settimeout(0.25)
            try:
                conn, _ = server_sock.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            with conn:
                if self._secret:
                    if not self.T.server_handshake(
                            conn, self._secret, time.monotonic() + 5.0):
                        continue
                self.T.serve_connection(conn, self.handle,
                                        should_stop=self._shutdown.is_set,
                                        send_hook=self._send_hook)


def main(argv=None):
    fail = os.environ.get("HVD_SERVE_WORKER_FAIL_START")
    if fail:
        print("serve_stub_worker: HVD_SERVE_WORKER_FAIL_START set",
              file=sys.stderr, flush=True)
        return int(fail)

    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", default="")
    ap.add_argument("--bind", default="",
                    help="tcp host:port instead of a unix socket "
                         "(real-worker parity: requires "
                         "HOROVOD_SECRET, handshake per connection)")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--heartbeat-dir", default="")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--tick-s", type=float, default=0.001,
                    help="artificial per-tick service time")
    ap.add_argument("--startup-delay", type=float, default=0.0,
                    help="sleep before binding (spawn-race tests)")
    args = ap.parse_args(argv)
    if bool(args.socket) == bool(args.bind):
        ap.error("exactly one of --socket / --bind required")

    if args.startup_delay > 0:
        time.sleep(args.startup_delay)

    T = _load_transport()
    import socket as _socket

    secret = None
    if args.bind:
        host, _, port_s = args.bind.rpartition(":")
        secret = os.environ.get("HOROVOD_SECRET", "")
        if not secret:
            print("serve_stub_worker: --bind needs HOROVOD_SECRET",
                  file=sys.stderr, flush=True)
            return 2
        srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port_s)))
        srv.listen(2)
    else:
        try:
            os.unlink(args.socket)
        except OSError:
            pass
        srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        srv.bind(args.socket)
        srv.listen(2)

    hb_path = ""
    if args.heartbeat_dir:
        os.makedirs(args.heartbeat_dir, exist_ok=True)
        hb_path = os.path.join(args.heartbeat_dir, f"hb-{args.rank}")

    host = StubHost(T, args.slots, hb_path, args.tick_s,
                    secret=secret)
    rpc = threading.Thread(target=host.rpc_loop, args=(srv,),
                           daemon=True)
    rpc.start()
    host.serve_loop()
    srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
