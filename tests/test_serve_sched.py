"""Scheduler policy/gate/eviction unit tests + metrics aggregation
(horovod_tpu/serve/{scheduler,metrics}.py) — host bookkeeping only, no
model in the loop (tests/test_serve_engine.py covers the composed
paths)."""

import jax
import numpy as np
import pytest

from horovod_tpu.models import parallel_lm as plm
from horovod_tpu.serve import (
    PagedKVCache,
    Request,
    ServeConfig,
    ServeEngine,
    Scheduler,
)
from horovod_tpu.serve.metrics import percentile, summarize
from horovod_tpu.serve.scheduler import pick_victim


def _cache(cfg):
    params = plm.init_lm_params(jax.random.PRNGKey(0), 32, 32, 1, 2, 4,
                                8)
    return PagedKVCache(params, cfg)


def _req(lp=4, n=4, **kw):
    return Request(prompt=np.zeros((lp,), np.int32), max_new_tokens=n,
                   **kw)


class TestQueuePolicy:
    def test_fcfs_keeps_arrival_order(self):
        cfg = ServeConfig(page_size=8, num_pages=16, policy="fcfs")
        s = Scheduler(_cache(cfg), cfg)
        a, b = _req(lp=12), _req(lp=2)
        s.submit(a), s.submit(b)
        assert s.pick_prefill(free_slots=1, in_flight=0) is a

    def test_sjf_prefers_short_prompts(self):
        cfg = ServeConfig(page_size=8, num_pages=16, policy="sjf")
        s = Scheduler(_cache(cfg), cfg)
        a, b, c = _req(lp=12), _req(lp=2), _req(lp=2)
        s.submit(a), s.submit(b), s.submit(c)
        assert s.pick_prefill(1, 0) is b    # stable: b before c
        assert s.pick_prefill(1, 0) is c
        assert s.pick_prefill(1, 0) is a

    def test_sjf_never_starves_evicted_requeues(self):
        """requeue()'s head-of-queue priority must survive the sjf
        sort: the evicted request's prompt GREW by its generated
        prefix, so a plain length sort would push it behind every
        shorter new arrival forever."""
        cfg = ServeConfig(page_size=8, num_pages=32, policy="sjf")
        s = Scheduler(_cache(cfg), cfg)
        evicted = _req(lp=10, n=6)
        evicted.generated = [1, 2]
        evicted.output = [1, 2]
        s.requeue(evicted)                  # now 12 tokens of prompt
        short = _req(lp=2)
        s.submit(short)
        assert s.pick_prefill(1, 0) is evicted
        assert s.pick_prefill(1, 0) is short


class TestGates:
    @pytest.mark.parametrize("slo,free,queued,want", [
        ("latency", 0, 1, True),
        ("throughput", 0, 1, False),
        ("throughput", 1, 1, True),
        ("balanced", 0, 1, False),
        ("balanced", 0, 2, True),     # backlog overrides
        ("balanced", 1, 1, True),
    ])
    def test_slo_gate_truth_table(self, slo, free, queued, want):
        cfg = ServeConfig(page_size=8, num_pages=32, slo=slo)
        s = Scheduler(_cache(cfg), cfg)
        for _ in range(queued):
            s.submit(_req())
        assert s.prefill_gate(free) is want
        got = s.pick_prefill(free, in_flight=0)
        assert (got is not None) is want

    def test_in_flight_limit_blocks_admission(self):
        cfg = ServeConfig(page_size=8, num_pages=32, decode_slots=2)
        s = Scheduler(_cache(cfg), cfg)
        s.submit(_req())
        assert s.pick_prefill(1, in_flight=cfg.in_flight_limit) is None
        assert s.pick_prefill(1, in_flight=0) is not None


class TestAdmission:
    def test_reserve_grants_worst_case_up_front(self):
        cfg = ServeConfig(page_size=8, num_pages=16)   # capacity 15
        c = _cache(cfg)
        s = Scheduler(c, cfg)
        r = _req(lp=8, n=9)                 # positions 16 -> 2 pages
        s.submit(r)
        assert s.pick_prefill(1, 0) is r
        assert c.allocator.in_use == 2
        assert np.count_nonzero(r.page_table) == 2

    def test_reserve_head_waits_rather_than_skips(self):
        """Admission failure keeps the queue head in place (no
        starvation-by-skip): nothing is admitted until pages free."""
        cfg = ServeConfig(page_size=8, num_pages=4)    # capacity 3
        c = _cache(cfg)
        s = Scheduler(c, cfg)
        held = c.allocator.alloc(2)
        big, small = _req(lp=8, n=9), _req(lp=2, n=2)
        s.submit(big), s.submit(small)
        assert s.pick_prefill(1, 0) is None     # big needs 2, 1 free
        c.allocator.free(held)
        assert s.pick_prefill(1, 0) is big

    def test_lazy_starts_with_one_page_and_grows(self):
        cfg = ServeConfig(page_size=8, num_pages=16, admission="lazy")
        c = _cache(cfg)
        s = Scheduler(c, cfg)
        r = _req(lp=8, n=17)                # would need 4 pages reserved
        s.submit(r)
        assert s.pick_prefill(1, 0) is r
        assert c.allocator.in_use == 1
        assert s.ensure_pages(r, last_pos=23, evict=lambda _: False)
        assert c.allocator.in_use == 3

    def test_release_returns_everything(self):
        cfg = ServeConfig(page_size=8, num_pages=16)
        c = _cache(cfg)
        s = Scheduler(c, cfg)
        r = _req(lp=8, n=9)
        s.submit(r)
        s.pick_prefill(1, 0)
        s.release(r)
        assert c.allocator.in_use == 0
        assert not r.pages and np.count_nonzero(r.page_table) == 0


class TestEviction:
    def test_victim_is_newest_never_requester(self):
        a, b, c = (_req(), _req(), _req())
        a.t_admit, b.t_admit, c.t_admit = 1.0, 3.0, 2.0
        assert pick_victim([a, b, c], requester=a) is b
        assert pick_victim([a, b, c], requester=b) is c
        assert pick_victim([a], requester=a) is None

    def test_requeue_extends_prompt_and_shrinks_budget(self):
        cfg = ServeConfig(page_size=8, num_pages=16)
        s = Scheduler(_cache(cfg), cfg)
        r = _req(lp=4, n=6)
        r.generated = [7, 9]
        r.output = [7, 9]
        assert s.requeue(r)
        assert r.prompt_len == 6 and list(r.prompt[-2:]) == [7, 9]
        assert r.max_new_tokens == 4 and r.generated == []
        assert r.state == "queued" and s.queue[0] is r
        # sample_index keeps counting the FULL stream
        assert r.sample_index == 4 + 2

    def test_requeue_with_nothing_left_reports_finished(self):
        cfg = ServeConfig(page_size=8, num_pages=16)
        s = Scheduler(_cache(cfg), cfg)
        r = _req(lp=4, n=2)
        r.generated = [1, 2]
        assert not s.requeue(r)
        assert r.state == "finished"

    def test_requeue_off_is_terminal(self):
        params = plm.init_lm_params(jax.random.PRNGKey(0), 64, 64, 1, 2,
                                    8, 32)
        cfg = ServeConfig(page_size=4, num_pages=8, decode_slots=2,
                          prefill_chunk=4, admission="lazy",
                          requeue_evicted=False)
        eng = ServeEngine(params, cfg)
        key = jax.random.PRNGKey(5)
        reqs = [eng.submit(
            np.asarray(jax.random.randint(
                jax.random.fold_in(key, i), (9,), 0, 64)), 10)
            for i in range(3)]
        eng.run(max_steps=300)
        states = {r.state for r in reqs}
        assert "evicted" in states
        assert eng.evicted and all(r.pages == [] for r in eng.evicted)


class TestRejectReasons:
    """Rejection carries its reason (the fleet router's load-shedding
    vocabulary, stamped engine-level too): ``infeasible`` = can never
    run on this geometry, ``overloaded`` = bounded queue full."""

    def test_infeasible_vs_overloaded(self):
        cfg = ServeConfig(page_size=8, num_pages=8, max_queue=1)
        sched = Scheduler(_cache(cfg), cfg)
        never = _req(lp=30, n=10)       # lp + n > Lmax = 32
        assert not sched.submit(never)
        assert never.state == "rejected"
        assert never.reject_reason == "infeasible"
        ok = _req()
        assert sched.submit(ok) and ok.reject_reason is None
        overflow = _req()
        assert not sched.submit(overflow)
        assert overflow.state == "rejected"
        assert overflow.reject_reason == "overloaded"


class TestMetrics:
    def test_percentile_nearest_rank(self):
        xs = [10.0, 20.0, 30.0, 40.0]
        assert percentile(xs, 50) == 20.0
        assert percentile(xs, 99) == 40.0
        assert percentile(xs, 100) == 40.0
        assert percentile([], 50) is None
        assert percentile([5.0], 99) == 5.0      # always a real sample

    def test_summarize_contract(self):
        r = _req(lp=4, n=3)
        r.arrival = 1.0
        r.t_first_token = 1.5
        r.token_times = [1.5, 1.7, 2.0]
        r.output = [1, 2, 3]
        r.state = "finished"
        s = summarize([r], wall_s=2.0, chips=2,
                      occupancy_samples=[0.25, 0.75])
        assert s["generated_tokens"] == 3
        assert s["tokens_per_sec_per_chip"] == 0.8      # 3/2.0/2
        assert s["ttft_ms"]["p50"] == 500.0
        # gaps: 200ms, 300ms
        assert s["tbt_ms"]["p50"] == 200.0
        assert s["tbt_ms"]["p99"] == 300.0
        assert s["pages"]["occupancy_mean"] == 0.5
        assert s["pages"]["occupancy_max"] == 0.75

    def test_summarize_empty(self):
        s = summarize([], wall_s=1.0)
        assert s["requests"] == 0
        assert s["ttft_ms"]["p50"] is None
        assert s["pages"]["occupancy_mean"] is None
