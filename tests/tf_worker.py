"""Subprocess worker for horovod_tpu.tf multi-process tests (the
rebuild's ``mpirun -np N test_tensorflow.py`` equivalent, SURVEY §4)."""

import os
import sys

import numpy as np


def run(scenario: str) -> None:
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import tensorflow as tf

    import horovod_tpu.tf as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    if scenario == "ops":
        # Closed-form allreduce (reference test_tensorflow.py:107-139).
        t = tf.range(48, dtype=tf.float32) * (rank + 1)
        out = hvd.allreduce(t, average=False)
        scale = sum(r + 1 for r in range(size))
        np.testing.assert_allclose(out.numpy(),
                                   np.arange(48, dtype=np.float32) * scale)
        avg = hvd.allreduce(tf.ones(5) * (rank + 1))
        np.testing.assert_allclose(avg.numpy(), scale / size)

        # fp16 compression round-trip restores the caller's dtype.
        c = hvd.allreduce(tf.ones(7, tf.float32) * (rank + 1),
                          average=False, compression=hvd.Compression.fp16)
        assert c.dtype == tf.float32
        np.testing.assert_allclose(c.numpy(), scale, atol=0.01)

        # Ragged allgather (reference test_tensorflow.py:430-504 pattern).
        g = tf.fill((rank + 1, 2), float(rank))
        gathered = hvd.allgather(g)
        assert gathered.shape[0] == sum(r + 1 for r in range(size))
        off = 0
        for r in range(size):
            assert (gathered.numpy()[off:off + r + 1] == r).all()
            off += r + 1

        # Broadcast from a non-zero root.
        b = hvd.broadcast(tf.fill((4,), float(rank)), root_rank=size - 1)
        assert (b.numpy() == size - 1).all()

        # Gradient registrations (reference tensorflow/mpi_ops.py:94-183):
        # grad(allreduce) == allreduce of upstream grad;
        # grad(broadcast) == summed on root, zero elsewhere.
        x = tf.Variable(tf.ones(4) * (rank + 1))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd.allreduce(x, average=False))
        np.testing.assert_allclose(tape.gradient(y, x).numpy(), float(size))

        v = tf.Variable(tf.ones(3))
        with tf.GradientTape() as tape:
            z = tf.reduce_sum(hvd.broadcast(v, root_rank=0))
        gv = tape.gradient(z, v).numpy()
        np.testing.assert_allclose(gv, float(size) if rank == 0 else 0.0)

        # grad(allgather): allreduce-sum of dy, sliced to this rank's
        # rows — with identical per-rank losses, sum-over-ranks
        # convention gives size (reference tensorflow/mpi_ops.py:127-148).
        xr = tf.Variable(tf.ones((rank + 1, 2)))  # ragged rows
        with tf.GradientTape() as tape:
            yg = tf.reduce_sum(hvd.allgather(xr))
        gg = tape.gradient(yg, xr)
        assert gg.shape == (rank + 1, 2), gg.shape
        np.testing.assert_allclose(gg.numpy(), float(size))

        # Sparse path (reference tensorflow/__init__.py:96-110):
        # IndexedSlices allreduce == allgather of values + indices.
        # Rank r contributes row r with value r+1; the densified result
        # must hold every rank's slice.
        sl = tf.IndexedSlices(
            tf.fill((1, 3), float(rank + 1)), tf.constant([rank]),
            dense_shape=tf.constant([size, 3], tf.int64))
        red = hvd.allreduce(sl, average=False)
        assert isinstance(red, tf.IndexedSlices), type(red)
        dense = tf.math.unsorted_segment_sum(red.values, red.indices,
                                             size).numpy()
        for r in range(size):
            np.testing.assert_allclose(dense[r], float(r + 1))

        # The same slices through DistributedGradientTape: an embedding
        # lookup's gradient arrives as IndexedSlices; averaged values,
        # and sparse_as_dense=True densifies to the same totals.
        emb = tf.Variable(tf.zeros((size + 1, 2)))
        with tf.GradientTape() as tape:
            y2 = tf.reduce_sum(tf.gather(emb, [rank]) * (rank + 1))
        dtape = hvd.DistributedGradientTape(tape)
        (ge,) = dtape.gradient(y2, [emb])
        assert isinstance(ge, tf.IndexedSlices)
        ge_dense = tf.math.unsorted_segment_sum(
            ge.values, ge.indices, size + 1).numpy()
        with tf.GradientTape() as tape:
            y3 = tf.reduce_sum(tf.gather(emb, [rank]) * (rank + 1))
        dtape2 = hvd.DistributedGradientTape(tape, sparse_as_dense=True)
        (gd2,) = dtape2.gradient(y3, [emb])
        assert not isinstance(gd2, tf.IndexedSlices)
        np.testing.assert_allclose(gd2.numpy(), ge_dense, atol=1e-6)
        for r in range(size):
            np.testing.assert_allclose(ge_dense[r], (r + 1) / size)

    elif scenario == "tape":
        # DistributedGradientTape end-to-end: disjoint data shards, SGD
        # on averaged gradients converges and params stay in lockstep
        # (reference test pattern, tensorflow/__init__.py:151-244).
        tf.random.set_seed(1234)  # same init everywhere
        w = tf.Variable(tf.random.normal((6, 1)))
        b = tf.Variable(tf.zeros((1,)))
        hvd.broadcast_variables([w, b], root_rank=0)

        rng = np.random.RandomState(100 + rank)  # different data
        w_true = np.ones((6, 1), np.float32)
        losses = []
        for _ in range(40):
            X = tf.constant(rng.randn(32, 6).astype(np.float32))
            y = X @ w_true
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean((X @ w + b - y) ** 2)
            dtape = hvd.DistributedGradientTape(tape)
            dw, db = dtape.gradient(loss, [w, b])
            w.assign_sub(0.05 * dw)
            b.assign_sub(0.05 * db)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])

        flat = np.concatenate([w.numpy().ravel(), b.numpy().ravel()])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(gathered.numpy()[r], flat,
                                       atol=1e-6,
                                       err_msg=f"rank {rank} vs {r}")

    elif scenario == "keras":
        # tf.keras fit with the two callbacks: broadcast start, averaged
        # epoch metrics (reference keras/callbacks.py).
        from horovod_tpu.tf.keras import (
            BroadcastGlobalVariablesCallback,
            MetricAverageCallback,
        )

        tf.random.set_seed(42 + rank)  # DIFFERENT init per rank
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")

        # Identical data on every rank: with the broadcast equalizing the
        # differently-seeded starts, identical end params prove the
        # callback ran (per-shard data + averaged grads is the
        # DistributedGradientTape scenario above).
        rng = np.random.RandomState(7)
        X = rng.randn(64, 4).astype(np.float32)
        y = (X @ np.ones((4, 1))).astype(np.float32)
        # shuffle=False: fit's shuffling draws from the global seed,
        # which deliberately differs per rank here.
        hist = model.fit(
            X, y, epochs=2, batch_size=16, verbose=0, shuffle=False,
            callbacks=[BroadcastGlobalVariablesCallback(0),
                       MetricAverageCallback()])
        assert len(hist.history["loss"]) == 2

        # Despite different seeds, the broadcast made starts identical
        # and identical data kept them identical.
        flat = np.concatenate(
            [v.numpy().ravel() for v in model.trainable_variables])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(
                gathered.numpy()[r], flat, atol=1e-6,
                err_msg=f"rank {rank} diverged from {r}")

        # keras DistributedOptimizer: DISJOINT per-rank data this time —
        # only averaged apply_gradients can keep params in lockstep.
        from horovod_tpu.tf.keras import DistributedOptimizer

        tf.random.set_seed(3)
        dmodel = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        dopt = DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        dmodel.compile(optimizer=dopt, loss="mse")
        rng = np.random.RandomState(50 + rank)  # different shards
        Xr = rng.randn(64, 4).astype(np.float32)
        yr = (Xr @ np.ones((4, 1))).astype(np.float32)
        dmodel.fit(Xr, yr, epochs=2, batch_size=16, verbose=0,
                   shuffle=False,
                   callbacks=[BroadcastGlobalVariablesCallback(0)])
        flat = np.concatenate(
            [v.numpy().ravel() for v in dmodel.trainable_variables])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(
                gathered.numpy()[r], flat, atol=1e-6,
                err_msg=f"DistributedOptimizer: rank {rank} vs {r}")

        # Embedding model under compiled fit: the gradients arrive as
        # IndexedSlices and must densify through the py_function hop;
        # disjoint data + averaged grads keep ranks in lockstep.
        tf.random.set_seed(5)
        emodel = tf.keras.Sequential([
            tf.keras.layers.Embedding(16, 4),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(1)])
        eopt = DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        emodel.compile(optimizer=eopt, loss="mse")
        rng = np.random.RandomState(60 + rank)
        Xe = rng.randint(0, 16, size=(64, 3)).astype(np.int32)
        ye = rng.randn(64, 1).astype(np.float32)
        emodel.fit(Xe, ye, epochs=1, batch_size=16, verbose=0,
                   shuffle=False,
                   callbacks=[BroadcastGlobalVariablesCallback(0)])
        flat = np.concatenate(
            [v.numpy().ravel() for v in emodel.trainable_variables])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(
                gathered.numpy()[r], flat, atol=1e-6,
                err_msg=f"embedding model: rank {rank} vs {r}")

        # LAZILY-BUILT model (no input_shape): zero variables exist at
        # on_train_begin, so the callback must defer the broadcast to
        # the first batch end (reference on_batch_end semantics) —
        # a train-begin-only broadcast would silently no-op and ranks
        # would diverge.
        tf.random.set_seed(1000 + rank)
        lazy = tf.keras.Sequential([tf.keras.layers.Dense(1)])
        lazy.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
        assert not lazy.variables, "premise: unbuilt model has no vars"
        lazy.fit(X, y, epochs=2, batch_size=16, verbose=0, shuffle=False,
                 callbacks=[BroadcastGlobalVariablesCallback(0)])
        flat = np.concatenate(
            [v.numpy().ravel() for v in lazy.trainable_variables])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(
                gathered.numpy()[r], flat, atol=1e-6,
                err_msg=f"lazy-built: rank {rank} diverged from {r}")

    elif scenario == "keras_lr":
        # LR warmup/schedule callbacks + load_model re-wrap (reference
        # _keras/callbacks.py:131-229, _keras/__init__.py:93-109; tested
        # as reference test/test_keras.py:62-185 tests the originals).
        import tempfile

        from horovod_tpu.tf.keras import (
            BroadcastGlobalVariablesCallback,
            DistributedOptimizer,
            LearningRateScheduleCallback,
            LearningRateWarmupCallback,
            load_model,
        )

        rng = np.random.RandomState(7)
        X = rng.randn(64, 4).astype(np.float32)
        y = (X @ np.ones((4, 1))).astype(np.float32)
        steps = 4  # 64 / bs 16

        # Warmup over 2 epochs: with size=2 the ramp is nontrivial.
        # At epoch e's last batch the fractional epoch is exactly e+1,
        # so logs["lr"] = base/size * ((e+1)(size-1)/warmup + 1) and
        # the final warmup epoch ends at precisely the base rate.
        base_lr = 0.08
        tf.random.set_seed(11)
        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        model.compile(optimizer=tf.keras.optimizers.SGD(base_lr),
                      loss="mse")
        hist = model.fit(
            X, y, epochs=3, batch_size=16, verbose=0, shuffle=False,
            callbacks=[LearningRateWarmupCallback(warmup_epochs=2),
                       BroadcastGlobalVariablesCallback(0)])
        seen = hist.history["lr"]
        expect = [base_lr / size * ((e + 1) * (size - 1) / 2 + 1)
                  for e in range(2)] + [base_lr]
        np.testing.assert_allclose(seen, expect, rtol=1e-5,
                                   err_msg=f"warmup ramp {seen}")

        # Staircase schedule: untouched before start_epoch, then x0.5.
        tf.random.set_seed(12)
        smodel = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        smodel.compile(optimizer=tf.keras.optimizers.SGD(0.1), loss="mse")
        shist = smodel.fit(
            X, y, epochs=2, batch_size=16, verbose=0, shuffle=False,
            callbacks=[LearningRateScheduleCallback(
                0.5, start_epoch=1, momentum_correction=False)])
        np.testing.assert_allclose(shist.history["lr"], [0.1, 0.05],
                                   rtol=1e-5)

        # load_model: train distributed w/ momentum, save, reload via
        # hvd.load_model, assert the re-wrap preserved lr + slot state,
        # then keep training on DISJOINT data — only a live averaged
        # apply keeps ranks in lockstep after the reload.
        tf.random.set_seed(13)
        dmodel = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(4,))])
        dopt = DistributedOptimizer(
            tf.keras.optimizers.SGD(0.05, momentum=0.9))
        dmodel.compile(optimizer=dopt, loss="mse")
        dmodel.fit(X, y, epochs=1, batch_size=16, verbose=0,
                   shuffle=False,
                   callbacks=[BroadcastGlobalVariablesCallback(0)])
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "model.keras")
            dmodel.save(path)
            loaded = load_model(path)
        lopt = loaded.optimizer
        assert getattr(type(lopt), "_hvd_distributed", False), \
            "loaded optimizer not re-wrapped"
        assert type(lopt).__name__ == "SGD"
        np.testing.assert_allclose(
            float(lopt.learning_rate.numpy()), 0.05, rtol=1e-6)
        # Keras rebuilds loaded slot paths without the container prefix
        # ("SGD/sequential_2_dense_2_kernel_momentum" saves, reloads as
        # "SGD/dense_2_kernel_momentum") — normalize before matching.
        import re as _re

        def slot_key(v):
            return _re.sub(r"sequential(_\d+)?_", "", v.path)

        old_vars = {slot_key(v): v.numpy() for v in dopt.variables}
        assert len(lopt.variables) == len(old_vars)
        for v in lopt.variables:
            assert slot_key(v) in old_vars, f"missing slot {v.path}"
            np.testing.assert_allclose(v.numpy(), old_vars[slot_key(v)],
                                       atol=1e-6, err_msg=v.path)
        rng = np.random.RandomState(90 + rank)  # disjoint shards
        Xr = rng.randn(64, 4).astype(np.float32)
        yr = (Xr @ np.ones((4, 1))).astype(np.float32)
        loaded.fit(Xr, yr, epochs=1, batch_size=16, verbose=0,
                   shuffle=False)
        flat = np.concatenate(
            [v.numpy().ravel() for v in loaded.trainable_variables])
        gathered = hvd.allgather(tf.constant(flat[None, :]))
        for r in range(size):
            np.testing.assert_allclose(
                gathered.numpy()[r], flat, atol=1e-6,
                err_msg=f"post-load fit: rank {rank} vs {r}")

    else:
        raise SystemExit(f"unknown scenario {scenario}")

    hvd.shutdown()


if __name__ == "__main__":
    run(sys.argv[1])
