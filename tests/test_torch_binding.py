"""Tests for horovod_tpu.torch (reference test/test_torch.py analogue).

Single-process size-1 semantics in-process; multi-process correctness via
spawned workers over the native TCP transport (the rebuild's ``mpirun -np
N`` harness, SURVEY §4).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest
import torch

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "torch_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(size: int, scenario: str, timeout=180):
    port = _free_port()
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = str(REPO) + os.pathsep + base_env.get(
        "PYTHONPATH", "")
    base_env.pop("JAX_PLATFORMS", None)
    procs = []
    for rank in range(size):
        env = dict(base_env)
        env.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(size),
            "HOROVOD_LOCAL_RANK": str(rank),
            "HOROVOD_LOCAL_SIZE": str(size),
            "HOROVOD_CONTROLLER": f"127.0.0.1:{port}",
            "HOROVOD_CYCLE_TIME": "1",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER), scenario], env=env, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    failures = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if p.returncode != 0:
            failures.append(
                f"rank {rank} rc={p.returncode}\n{err.decode()[-3000:]}")
    assert not failures, "\n".join(failures)


@pytest.fixture()
def hvd_torch():
    import horovod_tpu.torch as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


class TestSingleProcess:
    def test_basics(self, hvd_torch):
        assert hvd_torch.rank() == 0
        assert hvd_torch.size() == 1
        assert hvd_torch.local_rank() == 0
        assert hvd_torch.local_size() == 1
        assert hvd_torch.mpi_threads_supported() is False

    def test_allreduce_identity(self, hvd_torch):
        t = torch.randn(10)
        out = hvd_torch.allreduce(t)
        assert torch.allclose(out, t)

    def test_allreduce_average_identity(self, hvd_torch):
        t = torch.randn(10)
        assert torch.allclose(hvd_torch.allreduce(t, average=True), t)

    def test_allreduce_inplace(self, hvd_torch):
        t = torch.ones(5)
        hvd_torch.allreduce_(t)
        assert torch.allclose(t, torch.ones(5))

    def test_allreduce_average_int_rejected(self, hvd_torch):
        """average=True on an integer tensor must fail up front with
        guidance, not with torch's opaque in-place-div error at completion
        (round-1 advisory)."""
        t = torch.ones(5, dtype=torch.int64)
        with pytest.raises(ValueError, match="average=False"):
            hvd_torch.allreduce(t, average=True)
        # sum path still works
        out = hvd_torch.allreduce(t, average=False)
        assert (out == 1).all()

    def test_allreduce_inplace_noncontiguous(self, hvd_torch):
        t = torch.randn(4, 6).t()  # non-contiguous view
        assert not t.is_contiguous()
        ref = t.clone()
        hvd_torch.allreduce_(t)  # exercises the stage + copy-back path
        assert torch.allclose(t, ref)

    def test_allgather_identity(self, hvd_torch):
        t = torch.randn(3, 2)
        out = hvd_torch.allgather(t)
        assert torch.allclose(out, t)

    def test_broadcast_identity(self, hvd_torch):
        t = torch.randn(7)
        out = hvd_torch.broadcast(t, root_rank=0)
        assert torch.allclose(out, t)

    def test_grad_allreduce(self, hvd_torch):
        x = torch.randn(4, requires_grad=True)
        y = hvd_torch.allreduce(x)
        y.sum().backward()
        assert torch.allclose(x.grad, torch.ones(4))

    def test_grad_allgather(self, hvd_torch):
        x = torch.randn(3, 2, requires_grad=True)
        y = hvd_torch.allgather(x)
        y.sum().backward()
        assert torch.allclose(x.grad, torch.ones(3, 2))

    def test_grad_broadcast(self, hvd_torch):
        x = torch.randn(4, requires_grad=True)
        y = hvd_torch.broadcast(x, root_rank=0)
        y.sum().backward()
        assert torch.allclose(x.grad, torch.ones(4))

    def test_compression_fp16_roundtrip(self, hvd_torch):
        t = torch.randn(16)
        out = hvd_torch.allreduce(t, compression=hvd_torch.Compression.fp16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, t, atol=1e-2)

    def test_bfloat16_allreduce(self, hvd_torch):
        t = torch.ones(9, dtype=torch.bfloat16)
        out = hvd_torch.allreduce(t)
        assert out.dtype == torch.bfloat16
        assert torch.allclose(out.float(), torch.ones(9))

    def test_optimizer_size1(self, hvd_torch):
        model = torch.nn.Linear(4, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        loss = model(torch.randn(8, 4)).pow(2).mean()
        loss.backward()
        opt.step()  # size 1: no hooks registered, plain step

    def test_duplicate_parameter_names_rejected(self, hvd_torch):
        model = torch.nn.Linear(4, 2)
        params = list(model.named_parameters())
        dup = params + [params[0]]
        with pytest.raises(ValueError, match="not unique"):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=dup)

    def test_broadcast_parameters_state_dict(self, hvd_torch):
        model = torch.nn.Linear(4, 2)
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

    def test_broadcast_object_identity(self, hvd_torch):
        obj = {"epoch": 3, "lr": 0.1, "name": "resnet"}
        out = hvd_torch.broadcast_object(obj, root_rank=0)
        assert out == obj


class TestMultiProcess:
    @pytest.mark.parametrize("size", [2, 3])
    def test_ops(self, size):
        _spawn(size, "ops")

    def test_distributed_optimizer_converges(self):
        _spawn(2, "optimizer")

    def test_optimizer_features(self):
        _spawn(2, "optimizer_features")

    def test_init_comm_subworld(self):
        """hvd.init(comm=[0, 2]) on 3 launched processes: the pair runs
        collectives + DistributedOptimizer while rank 1 sits out on its
        singleton (reference common/__init__.py:58-84; round-3 verdict
        acceptance scenario on the public torch surface)."""
        _spawn(3, "subcomm")


def test_init_comm_out_of_world_rejected():
    """A comm naming ranks outside the launched world must raise, not
    silently run (round-1 standard: no knob parses to nothing). The
    full-world comm and None are both accepted (reference
    common/__init__.py:58-84 semantics)."""
    import pytest

    from horovod_tpu.native import NativeError

    import horovod_tpu.torch as hvd

    with pytest.raises(NativeError, match="outside the world"):
        hvd.init(comm=[0, 2])  # single-process world has no rank 2
    with pytest.raises(NativeError, match="empty"):
        hvd.init(comm=[])  # no knob parses to nothing
    hvd.init(comm=[0])  # == full single-process world: fine
    assert hvd.size() == 1
    hvd.shutdown()
