"""Coordinator stress test + sanitizer lanes (csrc/stress_test.cc).

The stress binary runs two ranks (fork before threads), each submitting
tensors from 4 concurrent app threads through negotiation / fusion /
stall detection while knob- and timeline-churn threads bang the C API
from outside the background loop — the exact coordinator surface the
reference exercised only single-threaded. The plain build is the fast
deadlock/corruption gate; the TSAN/ASAN builds (HVD_SANITIZE=thread|
address through the self-building loader) are the race/memory gates,
slow-marked and wired into tools/check.sh --sanitize.
"""

import os
import shutil
import subprocess

import pytest


def _cxx_available():
    return shutil.which(os.environ.get("CXX", "g++")) is not None


def _build(mode: str, monkeypatch):
    from horovod_tpu import native

    if mode:
        monkeypatch.setenv("HVD_SANITIZE", mode)
    else:
        monkeypatch.delenv("HVD_SANITIZE", raising=False)
    try:
        return native.build_stress_binary()
    except native.NativeBuildError as e:
        # Skip ONLY on a missing sanitizer toolchain: flag rejection
        # ("unrecognized ... '-fsanitize=thread'") or a missing runtime
        # at link time ("cannot find -ltsan/-lasan"). Bare "tsan"/"asan"
        # substrings would also match the build's own cache name
        # (hvdstress-<hash>-tsan) and turn every sanitizer-mode build
        # failure into a green-by-skip.
        missing_toolchain = ("fsanitize", "cannot find -ltsan",
                             "cannot find -lasan")
        if mode and any(s in str(e) for s in missing_toolchain):
            pytest.skip(f"toolchain lacks -fsanitize={mode}: {e}")
        raise


def _run(binary, extra_env=None, timeout=240):
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.run([str(binary)], env=env, capture_output=True,
                          text=True, timeout=timeout)
    return proc


@pytest.mark.skipif(not _cxx_available(), reason="no C++ toolchain")
def test_stress_binary_runs_clean(monkeypatch):
    binary = _build("", monkeypatch)
    proc = _run(binary)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "both ranks clean" in proc.stderr


@pytest.mark.skipif(not _cxx_available(), reason="no C++ toolchain")
def test_stress_clean_under_tsan(monkeypatch):
    """Acceptance gate: HVD_SANITIZE=thread rebuilds the native core and
    the concurrent-submission stress test runs race-clean under TSAN."""
    binary = _build("thread", monkeypatch)
    assert str(binary).endswith("-tsan")
    proc = _run(binary, extra_env={"TSAN_OPTIONS": "halt_on_error=0"})
    assert "WARNING: ThreadSanitizer" not in proc.stderr, proc.stderr[-8000:]
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "both ranks clean" in proc.stderr


@pytest.mark.skipif(not _cxx_available(), reason="no C++ toolchain")
def test_stress_clean_under_asan(monkeypatch):
    binary = _build("address", monkeypatch)
    assert str(binary).endswith("-asan")
    proc = _run(binary, extra_env={"ASAN_OPTIONS": "detect_leaks=1"})
    assert "ERROR: AddressSanitizer" not in proc.stderr, proc.stderr[-8000:]
    assert "ERROR: LeakSanitizer" not in proc.stderr, proc.stderr[-8000:]
    assert proc.returncode == 0, proc.stderr[-4000:]


def test_sanitize_mode_validation(monkeypatch):
    from horovod_tpu import native

    monkeypatch.setenv("HVD_SANITIZE", "bogus")
    with pytest.raises(native.NativeBuildError):
        native.sanitize_mode()
    monkeypatch.setenv("HVD_SANITIZE", "thread")
    assert native.sanitize_mode() == "thread"
    monkeypatch.setenv("HVD_SANITIZE", "")
    assert native.sanitize_mode() == ""


def test_sanitized_cache_names_are_distinct(monkeypatch):
    """Plain and sanitized builds must not collide in the content-hash
    cache — switching HVD_SANITIZE may never serve a stale flavor."""
    from horovod_tpu import native

    monkeypatch.delenv("HVD_SANITIZE", raising=False)
    h = native._source_hash()
    plain = f"libhvdtpu-{h}.so"
    monkeypatch.setenv("HVD_SANITIZE", "thread")
    suffix, flags = native._mode_suffix_flags(native.sanitize_mode())
    assert suffix == "-tsan" and "-fsanitize=thread" in flags
    monkeypatch.setenv("HVD_SANITIZE", "address")
    suffix2, flags2 = native._mode_suffix_flags(native.sanitize_mode())
    assert suffix2 == "-asan" and "-fsanitize=address" in flags2
    assert plain != f"libhvdtpu-{h}{suffix}.so" != f"libhvdtpu-{h}{suffix2}.so"
