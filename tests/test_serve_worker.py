"""Cross-process serving fleet (serve/worker.py + fleet transport=process).

Two lanes over the SAME fleet code paths:

* **stub lane (fast)** — real OS processes speaking the real framed
  protocol, but the worker is tests/serve_stub_worker.py (launched
  ``python -S``, ~30 ms start, no jax): covers the whole recovery
  matrix — genuine SIGKILL + reap + classification, torn-frame
  kill-mid-write, RPC deadline expiry, watchdog-caught stalls,
  close() escalation on a wedged worker, startup crashes — with the
  stub's context-hash "model" standing in for greedy decoding (next
  token depends on the full context, so redispatch continuation is
  bit-exact for the same reason it is on the real engine);
* **real-worker lane (slow)** — ``python -m horovod_tpu.serve.worker``
  end to end: greedy streams pinned BIT-IDENTICAL to ``lm_decode``
  across a real mid-run SIGKILL, a watchdog-classified stall, and a
  worker killed mid-write of a collect reply.
"""

import os
import signal
import sys
import time

import numpy as np
import pytest

from horovod_tpu.serve import (FleetConfig, ProcessReplica, ServeConfig,
                               ServeFleet)
from tests.serve_stub_worker import VOCAB, expected_stream

HERE = os.path.dirname(os.path.abspath(__file__))
STUB = os.path.join(HERE, "serve_stub_worker.py")

#: The stub never touches the params/engine; the fleet only reads
#: Lmax (admission geometry) off this.
STUB_PARAMS = {"pos": np.zeros((64, 4), np.float32)}


def _stub_cmd(extra_env=None, extra_args=(), per_rid_env=None):
    """worker_cmd hook launching the protocol stub with ``python -S``
    (no site-packages, no sitecustomize jax import — ~30 ms).
    ``per_rid_env`` applies to a replica's FIRST incarnation only —
    fault hooks must not re-fire on the relaunched worker."""

    def cmd(rid, sock_path, default):
        dcmd, denv = default
        hb_dir = dcmd[dcmd.index("--heartbeat-dir") + 1]
        argv = [sys.executable, "-S", STUB, "--socket", sock_path,
                "--rank", str(rid), "--heartbeat-dir", hb_dir,
                "--slots", "2"] + list(extra_args)
        env = dict(denv)
        env.update(extra_env or {})
        if f"r{rid}-1.sock" in sock_path:
            env.update((per_rid_env or {}).get(rid, {}))
        return argv, env

    return cmd


def _stub_fleet(worker_cmd=None, **fleet_kw):
    fleet_kw.setdefault("replicas", 2)
    fleet_kw.setdefault("transport", "process")
    fleet_kw.setdefault("backoff_base", 0.01)
    fleet_kw.setdefault("rpc_deadline", 10.0)
    return ServeFleet(STUB_PARAMS,
                      ServeConfig(page_size=8, num_pages=32,
                                  decode_slots=2, prefill_chunk=4),
                      FleetConfig(**fleet_kw),
                      worker_cmd=worker_cmd or _stub_cmd())


def _prompts(n, base=3):
    return [list(range(base + i, base + i + 4 + i % 3)) for i in range(n)]


def _assert_reaped(fl):
    for rep in fl.replicas:
        assert isinstance(rep, ProcessReplica)
        assert rep.proc.poll() is not None, (
            f"replica {rep.id} pid {rep.proc.pid} not reaped (zombie)")


def _run_until(fl, reqs, timeout=30.0):
    t0 = time.monotonic()
    while not fl.idle and time.monotonic() - t0 < timeout:
        fl.run(max_steps=fl.steps + 50)
        if not fl.idle:
            time.sleep(0.01)
    assert fl.idle, [r.state for r in reqs]


class TestStubFleet:
    def test_clean_run_streams_exact_and_close_reaps(self):
        fl = _stub_fleet()
        try:
            prompts = _prompts(5)
            reqs = [fl.submit(np.asarray(p, np.int32), 4 + i % 3)
                    for i, p in enumerate(prompts)]
            _run_until(fl, reqs)
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, r.orig_max_new)
            f = fl.stats()["fleet"]
            assert f["transport"] == "process"
            assert f["rpc_ms"]["calls"] > 0
            assert f["rpc_ms"]["p50"] is not None
            assert f["transport_incidents"] == {}
        finally:
            fl.close()
        _assert_reaped(fl)
        fl.close()   # idempotent

    def test_real_sigkill_classified_and_redispatched_exact(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"]))   # slow ticks: kill mid-run
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 8)
                    for p in prompts]
            for _ in range(4):
                fl.step()
            victim = fl.replicas[1]
            pid = victim.proc.pid
            fl.arm_fault_plan("kill:replica=1,at=0s")
            _run_until(fl, reqs)
            # the fault was a GENUINE SIGKILL of a real OS process
            assert victim.proc.poll() == -signal.SIGKILL or \
                fl.incidents[0]["code"] == -signal.SIGKILL
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["code"] == -signal.SIGKILL
            assert f["redispatched"] >= 1
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                # at-most-once + bit-exact continuation across the kill
                assert r.output == expected_stream(p, 8), (
                    pid, r.redispatches, r.output)
            assert any(r.redispatches for r in reqs)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_torn_frame_mid_write_routed_to_drain(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"],
            per_rid_env={1: {"HVD_SERVE_WORKER_TORN_COLLECT_AFTER": "4"}}))
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 8)
                    for p in prompts]
            _run_until(fl, reqs)
            f = fl.stats()["fleet"]
            # exactly one torn-frame incident, classified through the
            # real reaped exit code (the stub os._exit(1)s mid-write)
            assert f["transport_incidents"].get("FrameError") == 1, f
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["transport_error"] == "FrameError"
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, 8)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_rpc_deadline_expiry_is_replica_death(self):
        """A worker that never comes up (startup sleep >> deadline)
        resolves as DeadlineExceeded -> death path -> budget -> failed
        fleet sheds, inside the deadline budget — never a hang."""
        fl = _stub_fleet(replicas=1, max_restarts=0, rpc_deadline=0.4,
                         spawn_timeout=0.4,
                         worker_cmd=_stub_cmd(
                             extra_args=["--startup-delay", "30"]))
        try:
            r = fl.submit(np.asarray([1, 2, 3], np.int32), 4)
            t0 = time.monotonic()
            while fl.alive and time.monotonic() - t0 < 10:
                fl.step()
                time.sleep(0.01)
            assert not fl.alive
            assert time.monotonic() - t0 < 10
            f = fl.stats()["fleet"]
            assert f["transport_incidents"].get("DeadlineExceeded") == 1
            assert r.state == "rejected" and \
                r.reject_reason == "overloaded"
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_startup_crash_classified_before_first_heartbeat(self):
        """The troubleshooting-entry shape: a worker that dies on
        startup (before bind, before any heartbeat) is classified
        crashed via its real exit code and consumes restart budget."""
        fl = _stub_fleet(replicas=1, max_restarts=1,
                         worker_cmd=_stub_cmd(
                             extra_env={"HVD_SERVE_WORKER_FAIL_START":
                                        "3"}))
        try:
            r = fl.submit(np.asarray([1, 2, 3], np.int32), 4)
            t0 = time.monotonic()
            while fl.alive and time.monotonic() - t0 < 20:
                fl.step()
                time.sleep(0.01)
            f = fl.stats()["fleet"]
            # the initial spawn AND the budgeted relaunch both crash
            assert f["incidents_by_class"] == {"crashed": 2}, f
            assert all(i["code"] == 3 for i in f["incidents"])
            assert f["failed"] == 1
            assert f["restarts_used"] == 1
            assert r.state == "rejected"
            # no heartbeat was ever written for the dead incarnations
            assert not any(n.startswith("hb-") for n in
                           os.listdir(fl.heartbeat_dir))
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_stall_watchdog_kills_and_relaunches(self):
        """A stalled WORKER PROCESS stops stepping and heartbeating
        while its RPC thread stays up: only the stale heartbeat — the
        real PR-9 HealthWatchdog — catches it, classified stalled."""
        fl = _stub_fleet(watchdog_timeout=0.6,
                         worker_cmd=_stub_cmd(
                             extra_args=["--tick-s", "0.01"]))
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 12)
                    for p in prompts]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            _run_until(fl, reqs, timeout=30.0)
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"stalled": 1}, f
            assert f["detect_s"] is not None and f["detect_s"] >= 0.6
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, 12)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_close_reaps_a_wedged_worker(self):
        """The shutdown-hardening satellite: close() must reap a
        replica whose engine loop is genuinely wedged by a stall fault
        (graceful RPC first, SIGTERM -> SIGKILL escalation if needed),
        leave no zombies, and be idempotent."""
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.01"]))
        try:
            reqs = [fl.submit(np.asarray([1, 2, 3], np.int32), 50)]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            for _ in range(3):
                fl.step()
            time.sleep(0.1)   # let the wedge take hold
            assert reqs[0].state != "finished"
        finally:
            fl.close()
        _assert_reaped(fl)
        fl.close()   # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            fl.step()

    def test_constructor_spawn_failure_reaps_partial_fleet(self):
        """A failed spawn mid-__init__ must not orphan the worker
        processes already running (close() is unreachable when the
        constructor raises)."""
        spawned = []
        base = _stub_cmd()

        def cmd(rid, sock_path, default):
            if rid == 1:
                raise OSError("no such worker binary")
            argv, env = base(rid, sock_path, default)
            spawned.append(sock_path)
            return argv, env

        with pytest.raises(OSError, match="no such worker binary"):
            _stub_fleet(worker_cmd=cmd)
        assert spawned   # replica 0 really was launched first
        # ...and its process did not outlive the failed constructor
        import subprocess

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            # exec form: pgrep excludes itself (a shell wrapper would
            # self-match on the pattern in its own cmdline)
            ps = subprocess.run(["pgrep", "-f", "serve_stub_worker.py"],
                                capture_output=True, text=True)
            live = ps.stdout.split()
            if not live:
                break
            time.sleep(0.05)
        assert not live, live

    def test_slow_fault_rides_the_rpc(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.01"]))
        try:
            fl.arm_fault_plan("slow:replica=0,at=0s,factor=3")
            reqs = [fl.submit(np.asarray([5, 6, 7], np.int32), 4)]
            _run_until(fl, reqs)
            assert reqs[0].output == expected_stream([5, 6, 7], 4)
            assert fl.stats()["fleet"]["incidents_by_class"] == {}
        finally:
            fl.close()
        _assert_reaped(fl)


# ---------------------------------------------------------------- real


def _lm_setup():
    import jax

    from horovod_tpu.models import parallel_lm as plm

    V, LMAX = 64, 64
    params = plm.init_lm_params(jax.random.PRNGKey(0), V, LMAX, 2, 2,
                                8, 32)
    cfg = ServeConfig(page_size=8, num_pages=32, decode_slots=2,
                      prefill_chunk=4)
    return params, cfg, V


def _lm_ref(params, prompt, steps):
    import jax.numpy as jnp

    from horovod_tpu.models import parallel_lm as plm

    return list(np.asarray(
        plm.lm_decode(params, jnp.asarray(prompt)[None], steps))[0])


def _lm_prompts(v, n):
    import jax

    return [np.asarray(jax.random.randint(
        jax.random.fold_in(jax.random.PRNGKey(100), i), (8 + i,), 0, v),
        np.int32) for i in range(n)]


def _warm(fl):
    for _ in range(len(fl.replicas)):
        fl.submit(np.asarray([1, 2], np.int32), 2)
    fl.run()
    fl.reset_metrics()


class TestRealWorkerE2E:
    """python -m horovod_tpu.serve.worker end to end (slow: each worker
    spawn pays the sitecustomize jax import + first-step compile)."""

    def test_kill_redispatch_bit_exact_vs_lm_decode(self):
        params, cfg, V = _lm_setup()
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 10) for p in prompts]
            for _ in range(4):
                fl.step()
            fl.arm_fault_plan("kill:replica=1,at=0s")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["code"] == -signal.SIGKILL
            assert f["transport"] == "process"
            assert f["rpc_ms"]["p50"] is not None
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 10)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_stall_watchdog_classified_relaunch(self):
        params, cfg, V = _lm_setup()
        # The watchdog timeout must exceed the worst single worker
        # tick INCLUDING a compile (docs/serving.md "Process fleet").
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01,
                                    watchdog_timeout=8.0),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 4)
            reqs = [fl.submit(p, 16) for p in prompts]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"stalled": 1}, f
            assert f["detect_s"] >= 8.0
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 16)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_tcp_partition_host_down_bit_exact_vs_lm_decode(self):
        """Round-14 acceptance, real-worker edition: a 2-replica fleet
        on loopback TCP, the whole host network-partitioned mid-run —
        ONE classified host_down incident, both workers reaped and
        relaunched, and every greedy stream still bit-identical to
        lm_decode (the redispatch pin is transport-agnostic)."""
        params, cfg, V = _lm_setup()
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="tcp",
                                    backoff_base=0.01, max_restarts=4,
                                    rpc_deadline=60.0),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 10) for p in prompts]
            for _ in range(4):
                fl.step()
            fl.arm_fault_plan("partition:host=0,at=0s,secs=2")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["transport"] == "tcp"
            assert f["incidents_by_class"] == {"host_down": 1}, f
            assert f["host_incidents"] == 1
            assert f["failed"] == 0
            assert f["rpc_ms"]["p50"] is not None
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 10)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_kill_mid_write_torn_frame_redispatch_exact(self):
        """The satellite's e2e pin: a worker killed MID-WRITE of a
        collect reply leaves half a frame on the wire; the codec
        detects it (typed FrameError, no hang, no mis-parse), the
        fleet drains + redispatches, and every greedy stream is still
        bit-identical to lm_decode."""
        params, cfg, V = _lm_setup()

        def cmd(rid, sock_path, default):
            argv, env = default
            if rid == 1 and "r1-1" in sock_path:   # first incarnation
                env = dict(env,
                           HVD_SERVE_WORKER_TORN_COLLECT_AFTER="12")
            return argv, env

        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01),
                        worker_env={"JAX_PLATFORMS": "cpu"},
                        worker_cmd=cmd)
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 20) for p in prompts]
            fl.run()
            f = fl.stats()["fleet"]
            assert f["transport_incidents"].get("FrameError") == 1, f
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["transport_error"] == "FrameError"
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 20)
        finally:
            fl.close()
        _assert_reaped(fl)
