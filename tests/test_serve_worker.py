"""Cross-process serving fleet (serve/worker.py + fleet transport=process).

Two lanes over the SAME fleet code paths:

* **stub lane (fast)** — real OS processes speaking the real framed
  protocol, but the worker is tests/serve_stub_worker.py (launched
  ``python -S``, ~30 ms start, no jax): covers the whole recovery
  matrix — genuine SIGKILL + reap + classification, torn-frame
  kill-mid-write, RPC deadline expiry, watchdog-caught stalls,
  close() escalation on a wedged worker, startup crashes — with the
  stub's context-hash "model" standing in for greedy decoding (next
  token depends on the full context, so redispatch continuation is
  bit-exact for the same reason it is on the real engine);
* **real-worker lane (slow)** — ``python -m horovod_tpu.serve.worker``
  end to end: greedy streams pinned BIT-IDENTICAL to ``lm_decode``
  across a real mid-run SIGKILL, a watchdog-classified stall, and a
  worker killed mid-write of a collect reply.
"""

import os
import signal
import sys
import time

import numpy as np
import pytest

from horovod_tpu.serve import (FleetConfig, ProcessReplica, ServeConfig,
                               ServeFleet)
from tests.serve_stub_worker import VOCAB, expected_stream, params_salt

HERE = os.path.dirname(os.path.abspath(__file__))
STUB = os.path.join(HERE, "serve_stub_worker.py")

#: The stub never runs an engine off these, but the fleet ships them
#: to every worker incarnation as the wire params artifact (the
#: digest-derived salt below is the stub's "weights") and reads Lmax
#: (admission geometry) off them.
STUB_PARAMS = {"pos": np.zeros((64, 4), np.float32)}
#: Salt every stub incarnation decodes with once the fleet's wire-init
#: push lands — expected_stream(p, n, SALT) matching IS the proof the
#: artifact arrived over the transport, digest-intact.
SALT = params_salt(STUB_PARAMS)


def _stub_cmd(extra_env=None, extra_args=(), per_rid_env=None):
    """worker_cmd hook launching the protocol stub with ``python -S``
    (no site-packages, no sitecustomize jax import — ~30 ms).
    ``per_rid_env`` applies to a replica's FIRST incarnation only —
    fault hooks must not re-fire on the relaunched worker."""

    def cmd(rid, sock_path, default):
        dcmd, denv = default
        hb_dir = dcmd[dcmd.index("--heartbeat-dir") + 1]
        argv = [sys.executable, "-S", STUB, "--socket", sock_path,
                "--rank", str(rid), "--heartbeat-dir", hb_dir,
                "--slots", "2"] + list(extra_args)
        env = dict(denv)
        env.update(extra_env or {})
        if f"r{rid}-1.sock" in sock_path:
            env.update((per_rid_env or {}).get(rid, {}))
        return argv, env

    return cmd


def _stub_fleet(worker_cmd=None, **fleet_kw):
    fleet_kw.setdefault("replicas", 2)
    fleet_kw.setdefault("transport", "process")
    fleet_kw.setdefault("backoff_base", 0.01)
    fleet_kw.setdefault("rpc_deadline", 10.0)
    return ServeFleet(STUB_PARAMS,
                      ServeConfig(page_size=8, num_pages=32,
                                  decode_slots=2, prefill_chunk=4),
                      FleetConfig(**fleet_kw),
                      worker_cmd=worker_cmd or _stub_cmd())


def _prompts(n, base=3):
    return [list(range(base + i, base + i + 4 + i % 3)) for i in range(n)]


def _assert_reaped(fl):
    for rep in fl.replicas:
        assert isinstance(rep, ProcessReplica)
        assert rep.proc.poll() is not None, (
            f"replica {rep.id} pid {rep.proc.pid} not reaped (zombie)")


def _run_until(fl, reqs, timeout=30.0):
    t0 = time.monotonic()
    while not fl.idle and time.monotonic() - t0 < timeout:
        fl.run(max_steps=fl.steps + 50)
        if not fl.idle:
            time.sleep(0.01)
    assert fl.idle, [r.state for r in reqs]


class TestStubFleet:
    def test_clean_run_streams_exact_and_close_reaps(self):
        fl = _stub_fleet()
        try:
            prompts = _prompts(5)
            reqs = [fl.submit(np.asarray(p, np.int32), 4 + i % 3)
                    for i, p in enumerate(prompts)]
            _run_until(fl, reqs)
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, r.orig_max_new, SALT)
            f = fl.stats()["fleet"]
            assert f["transport"] == "process"
            assert f["rpc_ms"]["calls"] > 0
            assert f["rpc_ms"]["p50"] is not None
            assert f["transport_incidents"] == {}
        finally:
            fl.close()
        _assert_reaped(fl)
        fl.close()   # idempotent

    def test_real_sigkill_classified_and_redispatched_exact(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"]))   # slow ticks: kill mid-run
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 8)
                    for p in prompts]
            for _ in range(4):
                fl.step()
            victim = fl.replicas[1]
            pid = victim.proc.pid
            fl.arm_fault_plan("kill:replica=1,at=0s")
            _run_until(fl, reqs)
            # the fault was a GENUINE SIGKILL of a real OS process
            assert victim.proc.poll() == -signal.SIGKILL or \
                fl.incidents[0]["code"] == -signal.SIGKILL
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["code"] == -signal.SIGKILL
            assert f["redispatched"] >= 1
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                # at-most-once + bit-exact continuation across the kill
                assert r.output == expected_stream(p, 8, SALT), (
                    pid, r.redispatches, r.output)
            assert any(r.redispatches for r in reqs)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_torn_frame_mid_write_routed_to_drain(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"],
            per_rid_env={1: {"HVD_SERVE_WORKER_TORN_COLLECT_AFTER": "4"}}))
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 8)
                    for p in prompts]
            _run_until(fl, reqs)
            f = fl.stats()["fleet"]
            # exactly one torn-frame incident, classified through the
            # real reaped exit code (the stub os._exit(1)s mid-write)
            assert f["transport_incidents"].get("FrameError") == 1, f
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["transport_error"] == "FrameError"
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, 8, SALT)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_rpc_deadline_expiry_is_replica_death(self):
        """A worker that never comes up (startup sleep >> deadline)
        resolves as DeadlineExceeded -> death path -> budget -> failed
        fleet sheds, inside the deadline budget — never a hang."""
        fl = _stub_fleet(replicas=1, max_restarts=0, rpc_deadline=0.4,
                         spawn_timeout=0.4,
                         worker_cmd=_stub_cmd(
                             extra_args=["--startup-delay", "30"]))
        try:
            r = fl.submit(np.asarray([1, 2, 3], np.int32), 4)
            t0 = time.monotonic()
            while fl.alive and time.monotonic() - t0 < 10:
                fl.step()
                time.sleep(0.01)
            assert not fl.alive
            assert time.monotonic() - t0 < 10
            f = fl.stats()["fleet"]
            assert f["transport_incidents"].get("DeadlineExceeded") == 1
            assert r.state == "rejected" and \
                r.reject_reason == "overloaded"
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_startup_crash_classified_before_first_heartbeat(self):
        """The troubleshooting-entry shape: a worker that dies on
        startup (before bind, before any heartbeat) is classified
        crashed via its real exit code and consumes restart budget."""
        fl = _stub_fleet(replicas=1, max_restarts=1,
                         worker_cmd=_stub_cmd(
                             extra_env={"HVD_SERVE_WORKER_FAIL_START":
                                        "3"}))
        try:
            r = fl.submit(np.asarray([1, 2, 3], np.int32), 4)
            t0 = time.monotonic()
            while fl.alive and time.monotonic() - t0 < 20:
                fl.step()
                time.sleep(0.01)
            f = fl.stats()["fleet"]
            # the initial spawn AND the budgeted relaunch both crash
            assert f["incidents_by_class"] == {"crashed": 2}, f
            assert all(i["code"] == 3 for i in f["incidents"])
            assert f["failed"] == 1
            assert f["restarts_used"] == 1
            assert r.state == "rejected"
            # no heartbeat was ever written for the dead incarnations
            assert not any(n.startswith("hb-") for n in
                           os.listdir(fl.heartbeat_dir))
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_stall_watchdog_kills_and_relaunches(self):
        """A stalled WORKER PROCESS stops stepping and heartbeating
        while its RPC thread stays up: only the stale heartbeat — the
        real PR-9 HealthWatchdog — catches it, classified stalled."""
        fl = _stub_fleet(watchdog_timeout=0.6,
                         worker_cmd=_stub_cmd(
                             extra_args=["--tick-s", "0.01"]))
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 12)
                    for p in prompts]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            _run_until(fl, reqs, timeout=30.0)
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"stalled": 1}, f
            assert f["detect_s"] is not None and f["detect_s"] >= 0.6
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == expected_stream(p, 12, SALT)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_close_reaps_a_wedged_worker(self):
        """The shutdown-hardening satellite: close() must reap a
        replica whose engine loop is genuinely wedged by a stall fault
        (graceful RPC first, SIGTERM -> SIGKILL escalation if needed),
        leave no zombies, and be idempotent."""
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.01"]))
        try:
            reqs = [fl.submit(np.asarray([1, 2, 3], np.int32), 50)]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            for _ in range(3):
                fl.step()
            time.sleep(0.1)   # let the wedge take hold
            assert reqs[0].state != "finished"
        finally:
            fl.close()
        _assert_reaped(fl)
        fl.close()   # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            fl.step()

    def test_constructor_spawn_failure_reaps_partial_fleet(self):
        """A failed spawn mid-__init__ must not orphan the worker
        processes already running (close() is unreachable when the
        constructor raises)."""
        spawned = []
        base = _stub_cmd()

        def cmd(rid, sock_path, default):
            if rid == 1:
                raise OSError("no such worker binary")
            argv, env = base(rid, sock_path, default)
            spawned.append(sock_path)
            return argv, env

        with pytest.raises(OSError, match="no such worker binary"):
            _stub_fleet(worker_cmd=cmd)
        assert spawned   # replica 0 really was launched first
        # ...and its process did not outlive the failed constructor
        import subprocess

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            # exec form: pgrep excludes itself (a shell wrapper would
            # self-match on the pattern in its own cmdline)
            ps = subprocess.run(["pgrep", "-f", "serve_stub_worker.py"],
                                capture_output=True, text=True)
            live = ps.stdout.split()
            if not live:
                break
            time.sleep(0.05)
        assert not live, live

    def test_slow_fault_rides_the_rpc(self):
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.01"]))
        try:
            fl.arm_fault_plan("slow:replica=0,at=0s,factor=3")
            reqs = [fl.submit(np.asarray([5, 6, 7], np.int32), 4)]
            _run_until(fl, reqs)
            assert reqs[0].output == expected_stream([5, 6, 7], 4, SALT)
            assert fl.stats()["fleet"]["incidents_by_class"] == {}
        finally:
            fl.close()
        _assert_reaped(fl)


NEW_PARAMS = {"pos": np.ones((64, 4), np.float32) * 3.0}
NEW_SALT = params_salt(NEW_PARAMS)


def _run_update_until_done(fl, reqs, timeout=30.0):
    t0 = time.monotonic()
    while (not fl.idle or fl.update_active) \
            and time.monotonic() - t0 < timeout:
        if not fl.step():
            time.sleep(0.005)
    assert fl.idle and not fl.update_active, (
        [r.state for r in reqs], fl.update_active)


class TestStubRollingUpdate:
    """The versioned rolling update over REAL worker OS processes (the
    protocol stub): drain → chunked wire push → digest verify →
    readmit, one replica at a time, with the transfer fault lanes.
    NEW_PARAMS differ from STUB_PARAMS, so the salt CHANGES across the
    version boundary — a stream that mixed versions mid-decode would
    match neither expected_stream(..., SALT) nor (..., NEW_SALT)."""

    def test_update_rolls_both_replicas_streams_never_mix(self):
        assert SALT != NEW_SALT
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"]))
        try:
            prompts = _prompts(6)
            reqs = [fl.submit(np.asarray(p, np.int32), 8)
                    for p in prompts]
            for _ in range(3):
                fl.step()
            assert fl.update_params(NEW_PARAMS) == 2
            with pytest.raises(RuntimeError, match="in progress"):
                fl.update_params(NEW_PARAMS)
            late = [fl.submit(np.asarray(p, np.int32), 6)
                    for p in _prompts(3, base=40)]
            _run_update_until_done(fl, reqs + late)
            f = fl.stats()["fleet"]
            assert f["params_version"] == 2
            assert f["incidents_by_class"] == {}, f
            per = f["per_replica"]
            assert all(r["version"] == 2 for r in per), per
            shas = {r["params_sha"] for r in per}
            assert len(shas) == 1 and None not in shas
            # 2 spawn wire-inits + 2 update pushes (tests run with
            # no bench-style metrics reset)
            assert f["params_push"]["pushes"] == 4
            assert f["params_push"]["retries"] == 0
            # EVERY stream is entirely one version's output — the pin:
            # a mixed stream would match neither reference.
            for p, r in zip(prompts + _prompts(3, base=40),
                            reqs + late):
                assert r.state == "finished"
                n = r.orig_max_new
                old = expected_stream(p, n, SALT)
                new = expected_stream(p, n, NEW_SALT)
                assert r.output in (old, new), (p, r.output)
            # ...and a request submitted AFTER the roll completed can
            # only decode under the new version.
            post = fl.submit(np.asarray([9, 9, 9], np.int32), 5)
            _run_update_until_done(fl, [post])
            assert post.output == expected_stream([9, 9, 9], 5,
                                                  NEW_SALT)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_transfer_tear_classified_retry_resumes(self):
        """kill-the-wire mid-push: the transfer: fault tears the FIRST
        push attempt; the fleet classifies it, backs off, reconnects,
        resumes from the worker's verified offset — exactly one
        transfer retry, NO replica death, digests verified."""
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"]),
            push_chunk_bytes=64)
        try:
            reqs = [fl.submit(np.asarray(p, np.int32), 6)
                    for p in _prompts(4)]
            fl.arm_fault_plan("transfer:replica=0,at=0s")
            fl.update_params(NEW_PARAMS)
            _run_update_until_done(fl, reqs)
            f = fl.stats()["fleet"]
            assert f["params_push"]["retries"] == 1, f["params_push"]
            assert f["transfer_incidents"] == {"ConnectionLost": 1}, f
            assert f["incidents_by_class"] == {}, f
            assert all(r["version"] == 2 for r in f["per_replica"])
            # the update was armed before the first tick, so the spawn
            # wire-inits already shipped the v2 artifact: 2 pushes
            assert f["params_push"]["pushes"] == 2
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_corrupt_chunk_is_typed_checksum_retry(self):
        """A bit-flipped chunk must be REJECTED by the worker's
        per-chunk CRC (typed ChecksumError riding back as the remote
        error), retried, and the committed artifact digest-verified —
        a corrupted transfer can never become a silently wrong
        model."""
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"]),
            push_chunk_bytes=64)
        try:
            reqs = [fl.submit(np.asarray(p, np.int32), 6)
                    for p in _prompts(4)]
            fl.arm_fault_plan("corrupt:replica=1,at=0s")
            fl.update_params(NEW_PARAMS)
            _run_update_until_done(fl, reqs)
            f = fl.stats()["fleet"]
            assert f["params_push"]["retries"] == 1, f["params_push"]
            assert f["transfer_incidents"] == {"ChecksumError": 1}, f
            assert f["incidents_by_class"] == {}, f
            shas = {r["params_sha"] for r in f["per_replica"]}
            assert len(shas) == 1 and None not in shas
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_kill_mid_push_consumes_budget_then_relaunch_updates(self):
        """A worker that DIES mid-push (not just a torn wire) exhausts
        the push's retry budget fast (the process is observably dead),
        takes the classified replica-death path, and its relaunch
        wire-inits straight onto the NEW version."""
        fl = _stub_fleet(worker_cmd=_stub_cmd(
            extra_args=["--tick-s", "0.02"],
            per_rid_env={0: {"HVD_STUB_DIE_ON_PUSH_CHUNK": "2"}}),
            push_chunk_bytes=64, max_restarts=2)
        try:
            reqs = [fl.submit(np.asarray(p, np.int32), 6)
                    for p in _prompts(4)]
            # let the doomed worker finish its spawn-time wire init
            # (the die-hook counts push_chunk calls: the init push is
            # chunk 1, the update push dies)... the init itself is
            # chunk 1+2 with 64B chunks, so it dies DURING INIT —
            # which is fine: a startup-window death is the same lane.
            _run_update_until_done(fl, reqs, timeout=30.0)
            f = fl.stats()["fleet"]
            # the death was classified and budgeted, and the final
            # state is a fully-updated fleet (the relaunch wire-inits
            # from the current artifact)
            assert f["incidents_by_class"].get("crashed", 0) >= 1, f
            assert f["restarts_used"] >= 1
            assert all(r["version"] is not None
                       for r in f["per_replica"] if r["state"] == "healthy")
            for r in reqs:
                assert r.state == "finished"
        finally:
            fl.close()
        _assert_reaped(fl)


# ---------------------------------------------------------------- real


def _lm_setup():
    import jax

    from horovod_tpu.models import parallel_lm as plm

    V, LMAX = 64, 64
    params = plm.init_lm_params(jax.random.PRNGKey(0), V, LMAX, 2, 2,
                                8, 32)
    cfg = ServeConfig(page_size=8, num_pages=32, decode_slots=2,
                      prefill_chunk=4)
    return params, cfg, V


def _lm_ref(params, prompt, steps):
    import jax.numpy as jnp

    from horovod_tpu.models import parallel_lm as plm

    return list(np.asarray(
        plm.lm_decode(params, jnp.asarray(prompt)[None], steps))[0])


def _lm_prompts(v, n):
    import jax

    return [np.asarray(jax.random.randint(
        jax.random.fold_in(jax.random.PRNGKey(100), i), (8 + i,), 0, v),
        np.int32) for i in range(n)]


def _warm(fl):
    for _ in range(len(fl.replicas)):
        fl.submit(np.asarray([1, 2], np.int32), 2)
    fl.run()
    fl.reset_metrics()


class TestRealWorkerE2E:
    """python -m horovod_tpu.serve.worker end to end (slow: each worker
    spawn pays the sitecustomize jax import + first-step compile)."""

    def test_kill_redispatch_bit_exact_vs_lm_decode(self):
        params, cfg, V = _lm_setup()
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 10) for p in prompts]
            for _ in range(4):
                fl.step()
            fl.arm_fault_plan("kill:replica=1,at=0s")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["code"] == -signal.SIGKILL
            assert f["transport"] == "process"
            assert f["rpc_ms"]["p50"] is not None
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 10)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_stall_watchdog_classified_relaunch(self):
        params, cfg, V = _lm_setup()
        # The watchdog timeout must exceed the worst single worker
        # tick INCLUDING a compile (docs/serving.md "Process fleet").
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01,
                                    watchdog_timeout=8.0),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 4)
            reqs = [fl.submit(p, 16) for p in prompts]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("stall:replica=0,at=0s")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["incidents_by_class"] == {"stalled": 1}, f
            assert f["detect_s"] >= 8.0
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 16)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_tcp_partition_host_down_bit_exact_vs_lm_decode(self):
        """Round-14 acceptance, real-worker edition: a 2-replica fleet
        on loopback TCP, the whole host network-partitioned mid-run —
        ONE classified host_down incident, both workers reaped and
        relaunched, and every greedy stream still bit-identical to
        lm_decode (the redispatch pin is transport-agnostic)."""
        params, cfg, V = _lm_setup()
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="tcp",
                                    backoff_base=0.01, max_restarts=4,
                                    rpc_deadline=60.0),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 10) for p in prompts]
            for _ in range(4):
                fl.step()
            fl.arm_fault_plan("partition:host=0,at=0s,secs=2")
            fl.run()
            f = fl.stats()["fleet"]
            assert f["transport"] == "tcp"
            assert f["incidents_by_class"] == {"host_down": 1}, f
            assert f["host_incidents"] == 1
            assert f["failed"] == 0
            assert f["rpc_ms"]["p50"] is not None
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 10)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_tcp_rolling_update_torn_push_bit_exact_vs_lm_decode(self):
        """Round-15 acceptance, real-worker edition: a 2-replica
        loopback-TCP fleet (params/config over the wire only) rolls to
        a new weights version mid-traffic with the FIRST push attempt
        torn; the push classifies exactly one transfer retry and
        resumes, both replicas digest-verify the new version, every
        request finishes, and — the update re-pushing the same params
        content — every greedy stream is bit-identical to lm_decode
        within its pinned version."""
        params, cfg, V = _lm_setup()
        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="tcp",
                                    backoff_base=0.01, max_restarts=4,
                                    push_chunk_bytes=16384),
                        worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 10) for p in prompts]
            for _ in range(3):
                fl.step()
            fl.arm_fault_plan("transfer:replica=0,at=0s")
            fl.update_params(params)
            t0 = time.monotonic()
            while (not fl.idle or fl.update_active) \
                    and time.monotonic() - t0 < 120:
                if not fl.step():
                    time.sleep(0.005)
            f = fl.stats()["fleet"]
            assert f["params_push"]["retries"] == 1, f["params_push"]
            assert sum(f["transfer_incidents"].values()) == 1, f
            assert f["incidents_by_class"] == {}, f
            assert f["params_version"] == 2
            per = f["per_replica"]
            assert all(r["version"] == 2 for r in per), per
            assert len({r["params_sha"] for r in per}) == 1
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 10)
        finally:
            fl.close()
        _assert_reaped(fl)

    def test_kill_mid_write_torn_frame_redispatch_exact(self):
        """The satellite's e2e pin: a worker killed MID-WRITE of a
        collect reply leaves half a frame on the wire; the codec
        detects it (typed FrameError, no hang, no mis-parse), the
        fleet drains + redispatches, and every greedy stream is still
        bit-identical to lm_decode."""
        params, cfg, V = _lm_setup()

        def cmd(rid, sock_path, default):
            argv, env = default
            if rid == 1 and "r1-1" in sock_path:   # first incarnation
                env = dict(env,
                           HVD_SERVE_WORKER_TORN_COLLECT_AFTER="12")
            return argv, env

        fl = ServeFleet(params, cfg,
                        FleetConfig(replicas=2, transport="process",
                                    backoff_base=0.01),
                        worker_env={"JAX_PLATFORMS": "cpu"},
                        worker_cmd=cmd)
        try:
            _warm(fl)
            prompts = _lm_prompts(V, 6)
            reqs = [fl.submit(p, 20) for p in prompts]
            fl.run()
            f = fl.stats()["fleet"]
            assert f["transport_incidents"].get("FrameError") == 1, f
            assert f["incidents_by_class"] == {"crashed": 1}
            assert f["incidents"][0]["transport_error"] == "FrameError"
            for p, r in zip(prompts, reqs):
                assert r.state == "finished"
                assert r.output == _lm_ref(params, p, 20)
        finally:
            fl.close()
        _assert_reaped(fl)
