"""The logical-axis sharding layer (horovod_tpu/parallel/logical.py):
LogicalMesh resolution semantics, the canonical config string, the
bind()/module_axis thin-shim contract — and the ISSUE-17 acceptance
pins: composed stacks (dp x tp, dp x sp ulysses, tp x pp) built through
the registry must be BIT-EXACT against the pre-registry per-module
paths on the 8-way virtual CPU mesh, and the int8-EF/ZeRO state
sharding specs that now flow through the rules table must be unchanged
vs PR-10."""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.parallel as par
from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.parallel.logical import (
    DATA_AXIS,
    DEFAULT_RULES,
    LogicalMesh,
    bind,
    current_logical_mesh,
    format_mesh_config,
    logical_partition_specs,
    module_axis,
    parse_mesh_config,
)


# ------------------------------------------------------------ config string


class TestMeshConfig:
    def test_parse_roundtrip_canonicalizes_order(self):
        axes = parse_mesh_config("tp=4,dp=8,sp=2")
        assert axes == {"tp": 4, "dp": 8, "sp": 2}
        assert format_mesh_config(axes) == "dp=8,tp=4,sp=2"

    def test_unknown_axes_sort_after_known(self):
        assert (format_mesh_config({"zz": 2, "tp": 4})
                == "tp=4,zz=2")

    @pytest.mark.parametrize("bad", [
        "", "dp", "dp=banana", "dp=0", "dp=2,dp=4", "2=dp"])
    def test_invalid_configs_raise(self, bad):
        with pytest.raises(InvalidArgumentError):
            parse_mesh_config(bad)


# ----------------------------------------------------------- LogicalMesh


class TestLogicalMesh:
    def test_spec_resolves_through_rules_table(self, hvd):
        lm = LogicalMesh({"dp": 4, "tp": 2})
        assert lm.spec("batch") == P("dp")
        assert lm.spec("heads") == P("tp")
        assert lm.spec("mlp") == P("tp")
        assert lm.spec("batch", None, "heads") == P("dp", None, "tp")
        # Rules mapping to None, or to axes this mesh lacks, replicate.
        assert lm.spec("kv") == P(None)
        assert lm.spec("embed") == P(None)
        assert lm.spec("seq") == P(None)
        assert lm.spec() == P()

    def test_first_defined_rule_wins(self, hvd):
        # batch tries dp first, then the flat harness axis: on a
        # DATA_AXIS-only mesh the fallback rule resolves.
        lm = LogicalMesh({DATA_AXIS: 8})
        assert lm.spec("batch") == P(DATA_AXIS)
        assert lm.role_axis("data") == DATA_AXIS

    def test_unknown_logical_axis_raises(self, hvd):
        lm = LogicalMesh({"dp": 8})
        with pytest.raises(InvalidArgumentError, match="rules table"):
            lm.spec("hvd")  # raw physical axis where a logical name goes

    def test_duplicate_physical_mapping_raises(self, hvd):
        lm = LogicalMesh({"dp": 4, "tp": 2})
        with pytest.raises(InvalidArgumentError, match="more than one"):
            lm.spec("heads", "mlp")  # both resolve to tp

    def test_config_and_defines(self, hvd):
        lm = LogicalMesh.from_config("tp=2,dp=4")
        assert lm.config == "dp=4,tp=2"
        assert lm.defines("dp") and lm.defines("tp")
        assert not lm.defines("sp") and not lm.defines(DATA_AXIS)

    def test_wildcard_axis(self, hvd):
        lm = LogicalMesh({"dp": -1, "tp": 2},
                         devices=jax.devices()[:8])
        assert lm.axes == {"dp": 4, "tp": 2}

    def test_virtual_submesh_prefix(self, hvd):
        # dp=2,tp=2 on 8 exposed devices: a 4-device prefix sub-mesh.
        lm = LogicalMesh({"dp": 2, "tp": 2})
        assert math.prod(lm.axes.values()) == 4
        assert lm.mesh.devices.size == 4

    def test_custom_rules_table(self, hvd):
        rules = tuple(r for r in DEFAULT_RULES if r[0] != "embed") + (
            ("embed", "tp"),)
        lm = LogicalMesh({"dp": 4, "tp": 2}, rules=rules)
        assert lm.spec("embed") == P("tp")

    def test_logical_partition_specs_tree(self, hvd):
        lm = LogicalMesh({"dp": 4, "tp": 2})
        tree = {"x": ("batch", "embed"), "w": ("embed", "mlp")}
        specs = logical_partition_specs(tree, lm)
        assert specs == {"x": P("dp", None), "w": P(None, "tp")}
        with pytest.raises(InvalidArgumentError, match="bind"):
            logical_partition_specs(tree)


# -------------------------------------------------- bind() / module_axis


class TestModuleAxis:
    def test_unbound_legacy_fallbacks(self):
        assert current_logical_mesh() is None
        assert module_axis("data") == DATA_AXIS
        assert module_axis("tensor") == "tp"
        assert module_axis("seq") == "sp"
        assert module_axis("stage") == "pp"
        assert module_axis("expert") == "ep"

    def test_explicit_override_always_wins(self, hvd):
        lm = LogicalMesh({"dp": 8})
        with bind(lm):
            assert module_axis("data", "my_axis") == "my_axis"

    def test_bound_mesh_resolves_roles(self, hvd):
        lm = LogicalMesh({"dp": 4, "tp": 2})
        with bind(lm):
            assert current_logical_mesh() is lm
            assert module_axis("data") == "dp"
            assert module_axis("tensor") == "tp"
        assert current_logical_mesh() is None

    def test_bound_mesh_without_role_axis_raises(self, hvd):
        lm = LogicalMesh({"dp": 8})
        with bind(lm):
            with pytest.raises(InvalidArgumentError, match="role"):
                module_axis("tensor")

    def test_bind_nests_innermost_wins(self, hvd):
        outer = LogicalMesh({"dp": 8})
        inner = LogicalMesh({"tp": 8})
        with bind(outer):
            with bind(inner):
                assert module_axis("tensor") == "tp"
            assert module_axis("data") == "dp"


# ----------------------------------------- composed-stack equivalence pins
#
# The tentpole acceptance: stacks composed THROUGH the registry (bound
# LogicalMesh, axis defaults resolved by module_axis, in/out specs from
# lm.spec) must reproduce the pre-registry per-module paths (raw
# make_mesh + hand-spelled axis literals) bit-for-bit. np.array_equal,
# not allclose: the shims resolve to the same axis names before any
# tracing happens, so the compiled programs are identical.

from horovod_tpu.models import parallel_lm as plm  # noqa: E402

V, LMAX, LAYERS, H, DH, FFN = 32, 32, 4, 4, 8, 16
B, L = 4, 16


@pytest.fixture(scope="module")
def lm_setup():
    rng = jax.random.PRNGKey(7)
    params = plm.init_lm_params(rng, V, LMAX, LAYERS, H, DH, FFN)
    tokens = jax.random.randint(jax.random.fold_in(rng, 1), (B, L), 0, V)
    return params, tokens


class TestComposedEquivalence:
    def test_dp_tp_lm_bit_exact(self, hvd, lm_setup):
        """dp x tp transformer_lm: registry-composed forward equals the
        per-module path bit-for-bit."""
        params, tokens = lm_setup

        legacy_mesh = par.make_mesh({"dp": 4, "tp": 2})
        legacy = jax.jit(jax.shard_map(
            lambda p, t: plm.lm_apply(p, t, tp="tp"),
            mesh=legacy_mesh,
            in_specs=(plm.lm_param_specs(LAYERS, "tp"), P("dp", None)),
            out_specs=P("dp", None, None)))(params, tokens)

        lm = LogicalMesh.from_config("dp=4,tp=2")
        with bind(lm):
            tp_ax = module_axis("tensor")
            composed = jax.jit(jax.shard_map(
                lambda p, t: plm.lm_apply(p, t, tp=tp_ax),
                mesh=lm.mesh,
                in_specs=(plm.lm_param_specs(LAYERS, tp_ax),
                          lm.spec("batch")),
                out_specs=lm.spec("batch", None, None)))(params, tokens)

        assert np.array_equal(np.asarray(composed), np.asarray(legacy))

    def test_dp_ulysses_lm_bit_exact(self, hvd, lm_setup):
        """dp x sp(ulysses) on the LM's own q/k/v: the registry-composed
        ulysses attention (axis resolved from the bound mesh) equals the
        explicit-axis per-module path bit-for-bit."""
        params, tokens = lm_setup
        # Real transformer_lm activations: the first layer's projected
        # q/k/v at the dense path's values.
        x = params["embed"][tokens] + params["pos"][None, :L]
        q, k, v = plm._project_qkv(params["layers"][0], x, None)
        scale = 1.0 / math.sqrt(q.shape[-1])

        legacy_mesh = par.make_mesh({"dp": 2, "sp": 4})
        legacy = jax.jit(jax.shard_map(
            lambda a, b, c: par.ulysses_attention(
                a, b, c, axis="sp", causal=True, scale=scale),
            mesh=legacy_mesh,
            in_specs=(P("dp", "sp"),) * 3,
            out_specs=P("dp", "sp")))(q, k, v)

        lm = LogicalMesh.from_config("dp=2,sp=4")
        with bind(lm):
            composed = jax.jit(jax.shard_map(
                lambda a, b, c: par.ulysses_attention(
                    a, b, c, causal=True, scale=scale),
                mesh=lm.mesh,
                in_specs=(lm.spec("batch", "seq"),) * 3,
                out_specs=lm.spec("batch", "seq")))(q, k, v)

        assert np.array_equal(np.asarray(composed), np.asarray(legacy))

    def test_tp_pp_lm_bit_exact(self, hvd, lm_setup):
        """tp x pp transformer_lm: one tp-sharded transformer block per
        pipeline stage, composed through the registry (pipeline axis AND
        tensor axis from the bound mesh) vs explicit literals."""
        params, tokens = lm_setup
        rest, stacked = plm.stack_layers(params)

        from horovod_tpu.ops.attention import dot_product_attention

        def stage(tp_ax, layer, a):
            q, kk, vv = plm._project_qkv(layer, a, tp_ax)
            scale = 1.0 / math.sqrt(q.shape[-1])
            attn = dot_product_attention(q, kk, vv, causal=True,
                                         scale=scale)
            a = plm._attn_out_residual(layer, attn, a, tp_ax)
            return plm._ffn_residual(layer, a, tp_ax)

        def run(pp_ax, tp_ax, re, st, t):
            x = re["embed"][t] + re["pos"][None, :L]
            xm = x.reshape(2, B // 2, L, x.shape[-1])
            out = par.pipeline_apply(functools.partial(stage, tp_ax),
                                     st, xm, axis=pp_ax)
            return plm._logits(re, out.reshape(B, L, x.shape[-1]))

        def stacked_specs(pp_ax, tp_ax):
            per_layer = plm.lm_param_specs(1, tp_ax)["layers"][0]

            def lead(s):
                return P(pp_ax, *s)

            return {k: ({kk: lead(vv) for kk, vv in v.items()}
                        if isinstance(v, dict) else lead(v))
                    for k, v in per_layer.items()}

        rest_specs = {k: (P() if not isinstance(v, dict)
                          else {kk: P() for kk in v})
                      for k, v in rest.items()}

        legacy_mesh = par.make_mesh({"tp": 2, "pp": 4})
        legacy = jax.jit(jax.shard_map(
            functools.partial(run, "pp", "tp"), mesh=legacy_mesh,
            in_specs=(rest_specs, stacked_specs("pp", "tp"), P()),
            out_specs=P()))(rest, stacked, tokens)

        lm = LogicalMesh.from_config("tp=2,pp=4")
        with bind(lm):
            tp_ax = module_axis("tensor")
            pp_ax = module_axis("stage")
            composed = jax.jit(jax.shard_map(
                # axis=None inside: pipeline_apply resolves "stage"
                # from the bound mesh at trace time.
                functools.partial(run, None, tp_ax), mesh=lm.mesh,
                in_specs=(rest_specs, stacked_specs(pp_ax, tp_ax), P()),
                out_specs=lm.spec()))(rest, stacked, tokens)

        assert np.array_equal(np.asarray(composed), np.asarray(legacy))

    def test_dp_tp_matches_dense_single_device(self, hvd, lm_setup):
        """The composed stack is not just self-consistent: it reproduces
        the dense single-device math (fp32 tolerance — the collective
        reduction order differs from the dense einsum's)."""
        params, tokens = lm_setup
        dense = plm.lm_apply(params, tokens)
        lm = LogicalMesh.from_config("dp=4,tp=2")
        with bind(lm):
            tp_ax = module_axis("tensor")
            composed = jax.jit(jax.shard_map(
                lambda p, t: plm.lm_apply(p, t, tp=tp_ax),
                mesh=lm.mesh,
                in_specs=(plm.lm_param_specs(LAYERS, tp_ax),
                          lm.spec("batch")),
                out_specs=lm.spec("batch", None, None)))(params, tokens)
        np.testing.assert_allclose(np.asarray(composed),
                                   np.asarray(dense),
                                   rtol=2e-4, atol=2e-5)


# -------------------------------------- EF/ZeRO state specs via the table


class TestStateSpecsThroughRegistry:
    @staticmethod
    def _int8_ef_state(hvd):
        """An int8-EF hierarchical train state with real residual leaves
        (the ladder needs an inner domain > 1 to engage, same as
        test_hierarchical's _inner_size discipline)."""
        import contextlib

        import optax

        from horovod_tpu import models
        from horovod_tpu.common import state as _state

        @contextlib.contextmanager
        def inner_size(inner):
            st = _state.global_state()
            saved = st.config.hierarchical_inner_size
            st.config.hierarchical_inner_size = inner
            try:
                yield
            finally:
                st.config.hierarchical_inner_size = saved

        with inner_size(4):
            model = models.MNISTNet()
            state, _ = models.create_train_state(
                jax.random.PRNGKey(0), model,
                optax.sgd(0.1, momentum=0.9),
                jnp.zeros((1, 28, 28, 1)),
                compression=hvd.Compression.int8, hierarchical="on")
        return state

    def test_int8_ef_residual_specs_unchanged_vs_pr10(self, hvd):
        """models.state_partition_specs consults the registry for the
        data axis; unbound, the int8-EF residual specs must be exactly
        PR-10's P(DATA_AXIS) — and every other leaf spec is unchanged
        too (the whole spec tree is compared, not just residuals)."""
        from horovod_tpu import models
        from horovod_tpu.jax.optimizer import ef_state_partition_specs

        state = self._int8_ef_state(hvd)
        spec = models.state_partition_specs(state)
        # PR-10 contract: rank-local residual leaves shard over the
        # flat harness axis, everything else replicates.
        expected = ef_state_partition_specs(state["opt_state"],
                                            axis_name=DATA_AXIS)
        got = ef_state_partition_specs(state["opt_state"])
        assert jax.tree_util.tree_structure(expected) \
            == jax.tree_util.tree_structure(got)
        assert jax.tree_util.tree_leaves(expected) \
            == jax.tree_util.tree_leaves(got)
        leaves = jax.tree_util.tree_leaves(spec)
        assert P(DATA_AXIS) in leaves
        assert set(leaves) <= {P(), P(DATA_AXIS)}

    def test_state_specs_follow_bound_mesh(self, hvd):
        """With a dp-stack LogicalMesh bound, the same state's specs
        resolve through the rules table to the stack's data axis."""
        from horovod_tpu import models

        state = self._int8_ef_state(hvd)
        lm = LogicalMesh({"dp": 8})
        with bind(lm):
            spec = models.state_partition_specs(state)
        leaves = jax.tree_util.tree_leaves(spec)
        assert P("dp") in leaves
        assert P(DATA_AXIS) not in leaves

    def test_zero_state_specs_follow_bound_mesh(self, hvd):
        """sharded_distributed_optimizer's scatter specs resolve the
        data axis the same way (zero.state_partition_specs)."""
        import optax

        from horovod_tpu.jax import zero

        params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}
        opt = zero.sharded_distributed_optimizer(optax.adam(1e-3))
        opt_state = opt.init(params)
        unbound = zero.state_partition_specs(opt_state)
        assert P(DATA_AXIS) in jax.tree_util.tree_leaves(unbound)
        lm = LogicalMesh({"dp": 8})
        with bind(lm):
            bound = zero.state_partition_specs(opt_state)
        leaves = jax.tree_util.tree_leaves(bound)
        assert P("dp") in leaves
        assert P(DATA_AXIS) not in leaves
