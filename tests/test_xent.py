"""Chunked fused cross-entropy (ops/xent.py) vs the dense composition.

The dense reference materializes [T, V] logits and log-softmaxes them —
exactly what the LM bench's unfused loss does (bench.py bench_lm); the
fused op must match its loss and gradients while never building the
full logits tensor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.xent import fused_cross_entropy


def _dense_nll(h, w, targets):
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[:, None], -1))


@pytest.mark.parametrize("t,chunk", [(64, 16), (60, 16), (16, 16)])
def test_fused_ce_matches_dense(t, chunk):
    """Loss + dh + dw exact vs dense, incl. a non-divisible token count
    (60 % 16 != 0 exercises the pad/weight path)."""
    key = jax.random.PRNGKey(0)
    e, v = 32, 97
    h = jax.random.normal(key, (t, e), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (e, v), jnp.float32)
    targets = jax.random.randint(jax.random.fold_in(key, 2), (t,), 0, v)

    ld, (gdh, gdw) = jax.value_and_grad(_dense_nll, argnums=(0, 1))(
        h, w, targets)
    lf, (fdh, fdw) = jax.value_and_grad(
        lambda h, w: fused_cross_entropy(h, w, targets, chunk),
        argnums=(0, 1))(h, w)

    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fdh), np.asarray(gdh),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(fdw), np.asarray(gdw),
                               rtol=1e-5, atol=1e-6)


def test_fused_ce_bf16_hidden():
    """bf16 hidden states (the LM's compute dtype): fp32 accumulation
    inside, gradients returned in the input dtypes."""
    key = jax.random.PRNGKey(3)
    t, e, v = 48, 16, 53
    h = jax.random.normal(key, (t, e), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(key, 1), (e, v), jnp.float32)
    targets = jax.random.randint(jax.random.fold_in(key, 2), (t,), 0, v)

    ld, (gdh, gdw) = jax.value_and_grad(
        lambda h, w: _dense_nll(h.astype(jnp.float32), w, targets),
        argnums=(0, 1))(h, w)
    lf, (fdh, fdw) = jax.value_and_grad(
        lambda h, w: fused_cross_entropy(
            h.astype(jnp.float32), w, targets, 16),
        argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    assert fdh.dtype == h.dtype and fdw.dtype == w.dtype
    np.testing.assert_allclose(np.asarray(fdh, np.float32),
                               np.asarray(gdh, np.float32),
                               rtol=2e-2, atol=1e-3)
    np.testing.assert_allclose(np.asarray(fdw), np.asarray(gdw),
                               rtol=1e-4, atol=1e-5)


def test_fused_ce_never_builds_full_logits():
    """Structural guarantee: the jaxpr of the fused op contains no
    [T, V]-shaped intermediate when T spans multiple chunks."""
    t, e, v, chunk = 64, 8, 331, 16
    h = jnp.zeros((t, e), jnp.float32)
    w = jnp.zeros((e, v), jnp.float32)
    targets = jnp.zeros((t,), jnp.int32)

    jaxpr = jax.make_jaxpr(
        jax.grad(lambda h: fused_cross_entropy(h, w, targets, chunk)))(h)

    def subjaxprs(params):
        for val in params.values():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v_ in vals:
                if hasattr(v_, "jaxpr"):     # ClosedJaxpr
                    yield v_.jaxpr
                elif hasattr(v_, "eqns"):    # raw Jaxpr
                    yield v_

    def walk(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                yield getattr(var.aval, "shape", ())
            for sub in subjaxprs(eqn.params):
                yield from walk(sub)

    shapes = list(walk(jaxpr.jaxpr))
    # Scan internals may carry [chunk, V] blocks; anything with BOTH a
    # full token axis and a full vocab axis (incl. padded variants,
    # anywhere in nested scan/remat jaxprs) is the HBM sink this op
    # exists to remove.
    offenders = [s for s in shapes
                 if len(s) >= 2 and s[-2] >= t and s[-1] >= v]
    assert not offenders, offenders


class TestVocabParallel:
    """tp_vocab_cross_entropy inside shard_map vs the dense NLL."""

    def _mesh(self, n):
        from horovod_tpu import parallel as par
        return par.make_mesh({"tp": n}, devices=jax.devices()[:n])

    @pytest.mark.parametrize("t,chunk", [(32, 8), (28, 8)])
    def test_loss_and_grads_match_dense(self, t, chunk):
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        key = jax.random.PRNGKey(7)
        e, v = 16, 64  # v_local = 16 per rank
        h = jax.random.normal(key, (t, e), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(key, 1), (e, v),
                              jnp.float32)
        targets = jax.random.randint(jax.random.fold_in(key, 2), (t,),
                                     0, v)

        from horovod_tpu.ops.xent import tp_vocab_cross_entropy

        def loss_vp(h, w):
            fn = jax.shard_map(
                lambda hh, ww: tp_vocab_cross_entropy(
                    hh, ww, targets, "tp", chunk),
                mesh=mesh, in_specs=(P(), P(None, "tp")), out_specs=P())
            return fn(h, w)

        ld, (gdh, gdw) = jax.value_and_grad(_dense_nll, argnums=(0, 1))(
            h, w, targets)
        lv, (vdh, vdw) = jax.value_and_grad(loss_vp, argnums=(0, 1))(h, w)

        np.testing.assert_allclose(float(lv), float(ld), rtol=1e-6)
        from horovod_tpu.parallel._vma import vma_typing_available
        if not vma_typing_available():
            # Legacy (check_rep-era) runtimes: the loss is exact (above)
            # but differentiating THROUGH the shard_map boundary cannot
            # coexist with the op's in-region gradient convention — the
            # legacy fallback (_vp_plain) corrects for in-region
            # transposes (what every in-repo caller does; pinned below
            # in test_loss_and_grads_match_dense_in_region), and without
            # vma typing the boundary transpose double-corrects dw.
            # Tracking: ops/xent.py _vp_plain docstring.
            pytest.xfail("legacy check_rep boundary transpose cannot "
                         "express the op's in-region gradient "
                         "convention (dw scales by tp size); in-region "
                         "grads are pinned exact on this runtime")
        np.testing.assert_allclose(np.asarray(vdh), np.asarray(gdh),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vdw), np.asarray(gdw),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("t,chunk", [(32, 8), (28, 8)])
    def test_loss_and_grads_match_dense_in_region(self, t, chunk):
        """The op's supported gradient convention on EVERY runtime: a
        ``jax.grad`` taken INSIDE the shard_map region (how
        models/parallel_lm.py's fused vocab-parallel loss differentiates
        it) yields the assembled dh (axis-invariant) and the rank-local
        dw slice — exactly the dense gradients."""
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        key = jax.random.PRNGKey(7)
        e, v = 16, 64  # v_local = 16 per rank
        h = jax.random.normal(key, (t, e), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(key, 1), (e, v),
                              jnp.float32)
        targets = jax.random.randint(jax.random.fold_in(key, 2), (t,),
                                     0, v)

        from horovod_tpu.ops.xent import tp_vocab_cross_entropy

        def region(hh, ww):
            def loss_fn(hh_, ww_):
                return tp_vocab_cross_entropy(hh_, ww_, targets, "tp",
                                              chunk)
            loss, (dh, dw) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(hh, ww)
            return loss, dh, dw

        fn = jax.shard_map(region, mesh=mesh,
                           in_specs=(P(), P(None, "tp")),
                           out_specs=(P(), P(), P(None, "tp")))
        lv, vdh, vdw = fn(h, w)

        ld, (gdh, gdw) = jax.value_and_grad(_dense_nll, argnums=(0, 1))(
            h, w, targets)
        np.testing.assert_allclose(float(lv), float(ld), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(vdh), np.asarray(gdh),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vdw), np.asarray(gdw),
                                   rtol=1e-5, atol=1e-6)

    def test_loss_identical_on_every_rank(self):
        """The op's contract: the returned scalar is axis-invariant
        (same value on every tp rank) — out_specs=P() above would fail
        loudly on mismatch, but pin it explicitly via a per-rank
        output."""
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        key = jax.random.PRNGKey(9)
        t, e, v = 16, 8, 32
        h = jax.random.normal(key, (t, e), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(key, 1), (e, v),
                              jnp.float32)
        targets = jax.random.randint(jax.random.fold_in(key, 2), (t,),
                                     0, v)

        from horovod_tpu.ops.xent import tp_vocab_cross_entropy

        fn = jax.shard_map(
            lambda hh, ww: tp_vocab_cross_entropy(
                hh, ww, targets, "tp", 8)[None],
            mesh=mesh, in_specs=(P(), P(None, "tp")),
            out_specs=P("tp"))
        per_rank = np.asarray(fn(h, w))
        np.testing.assert_allclose(per_rank, per_rank[0], rtol=0)
