"""horovod_tpu.elastic: snapshots, manifests, signals, fault injection,
exit-code classification, supervised restart — and the end-to-end
acceptance path: a fault-injected `hvdrun --elastic` job that loses a
rank mid-run and still finishes bit-exactly equal to the fault-free run.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import elastic
from horovod_tpu.common.exceptions import HorovodTimeoutError
from horovod_tpu.elastic.faults import FaultPlanError
from horovod_tpu.flax.checkpoint import CheckpointManager
from horovod_tpu.run import (JobResult, WorkerExit, classify_exit,
                             launch_job, _kill_all, _spawn_local)
from horovod_tpu.run.driver import EXIT_PREEMPTED, EXIT_USAGE

REPO = Path(__file__).resolve().parent.parent


def _clean_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("HOROVOD_FAULT_PLAN", None)
    return env


# ----------------------------------------------------------------- fixtures


def _toy_step():
    def step_fn(state, batch):
        g = batch["x"] * state["w"]
        return ({"w": state["w"] - 0.1 * g, "step": state["step"] + 1},
                {"loss": jnp.sum(state["w"])})

    def batch_for(step):
        return {"x": jnp.float32(step % 5 + 1)}

    init = {"w": jnp.float32(2.0), "step": jnp.int32(0)}
    return step_fn, batch_for, init


# ---------------------------------------------------------------- FaultPlan


class TestFaultPlan:
    def test_parse_grammar(self):
        plan = elastic.parse_fault_plan(
            "kill:rank=1,step=7; stall:rank=2,step=12,secs=0.5;"
            "preempt:rank=0,step=3,attempt=1;exit:rank=0,step=2,code=9")
        kinds = [a.kind for a in plan]
        assert kinds == ["kill", "stall", "preempt", "exit"]
        assert plan[0].rank == 1 and plan[0].step == 7
        assert plan[0].attempt == 0  # default: first launch only
        assert plan[1].secs == 0.5
        assert plan[2].attempt == 1
        assert plan[3].code == 9
        assert elastic.parse_fault_plan("") == []
        assert elastic.parse_fault_plan("  ;  ") == []

    @pytest.mark.parametrize("bad", [
        "explode:rank=0,step=1",          # unknown kind
        "kill:rank=0",                    # missing step
        "kill:step=3",                    # missing rank
        "kill:rank=zero,step=1",          # non-numeric
        "kill:rank=0,step=1,flavor=spicy",  # unknown key
        "kill rank=0 step=1",             # no colon
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(FaultPlanError):
            elastic.parse_fault_plan(bad)

    def test_injector_filters_rank_and_attempt(self):
        plan = elastic.parse_fault_plan(
            "exit:rank=0,step=5;exit:rank=1,step=5;"
            "exit:rank=0,step=9,attempt=1")
        inj = elastic.FaultInjector(plan, rank=0, attempt=1)
        assert [a.step for a in inj.pending] == [9]
        inj0 = elastic.FaultInjector(plan, rank=1, attempt=0)
        assert [a.step for a in inj0.pending] == [5]

    def test_exit_action_fires_once_at_boundary(self):
        plan = elastic.parse_fault_plan("exit:rank=0,step=5,code=7")
        inj = elastic.FaultInjector(plan, rank=0, attempt=0)
        inj.maybe_inject(4)  # below the step: nothing
        with pytest.raises(SystemExit) as ei:
            inj.maybe_inject(6)  # first boundary past step=5
        assert ei.value.code == 7
        inj.maybe_inject(7)  # consumed: does not re-fire

    def test_stall_action_sleeps_bounded(self):
        plan = elastic.parse_fault_plan("stall:rank=0,step=1,secs=0.2")
        inj = elastic.FaultInjector(plan, rank=0, attempt=0)
        t0 = time.monotonic()
        inj.maybe_inject(1)
        assert 0.15 <= time.monotonic() - t0 < 5.0

    def test_preempt_action_triggers_handler_not_signal(self):
        handler = elastic.PreemptionHandler(install=False)
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("preempt:rank=0,step=2"),
            rank=0, attempt=0)
        inj.maybe_inject(2, preemption=handler)
        assert handler.triggered

    def test_env_construction(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FAULT_PLAN", "kill:rank=3,step=11")
        monkeypatch.setenv("HOROVOD_RANK", "3")
        monkeypatch.setenv("HOROVOD_ELASTIC_RESTART", "0")
        inj = elastic.FaultInjector.from_env()
        assert [a.kind for a in inj.pending] == ["kill"]

    def test_parse_resize(self):
        plan = elastic.parse_fault_plan(
            "resize:rank=0,step=7,n=1;resize:rank=0,step=3,n=4,attempt=1")
        assert [a.n for a in plan] == [1, 4]
        assert elastic.resize_requests(plan) == {0: 1, 1: 4}
        assert "n=1" in str(plan[0])

    @pytest.mark.parametrize("bad", [
        "resize:rank=0,step=7",          # n missing
        "resize:rank=0,step=7,n=0",      # empty world
        "kill:rank=0,step=7,n=2",        # n on a non-resize kind
        # two resizes on one attempt: relaunch size would be ambiguous
        "resize:rank=0,step=3,n=1;resize:rank=1,step=9,n=2",
    ])
    def test_parse_resize_rejects(self, bad):
        with pytest.raises(FaultPlanError):
            elastic.parse_fault_plan(bad)

    def test_resize_action_triggers_handler_with_resized_code(self):
        handler = elastic.PreemptionHandler(install=False)
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("resize:rank=0,step=2,n=1"),
            rank=0, attempt=0)
        inj.maybe_inject(2, preemption=handler)
        assert handler.triggered
        assert handler.exit_code == elastic.EXIT_RESIZED

    def test_resize_action_without_handler_exits_resized(self):
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("resize:rank=0,step=2,n=1"),
            rank=0, attempt=0)
        with pytest.raises(SystemExit) as ei:
            inj.maybe_inject(2)
        assert ei.value.code == elastic.EXIT_RESIZED


# ----------------------------------------------------------------- manifest


class TestManifest:
    def test_round_trip_and_latest(self, tmp_path):
        d = str(tmp_path)
        m1 = elastic.ResumeManifest(step=3, world_size=2, rank=0,
                                    cursor={"epoch": 0, "offset": 12},
                                    rng_key=[1, 2])
        m2 = elastic.ResumeManifest(step=6, world_size=2, rank=0,
                                    cursor={"epoch": 0, "offset": 24})
        elastic.write_manifest(d, m1)
        elastic.write_manifest(d, m2)
        assert elastic.manifest_steps(d) == [3, 6]
        latest = elastic.latest_manifest(d)
        assert latest.step == 6 and latest.cursor["offset"] == 24
        old = elastic.read_manifest(d, 3)
        assert old.rng_key == [1, 2]
        assert np.array_equal(old.rng(), np.asarray([1, 2], np.uint32))

    def test_latest_survives_torn_pointer(self, tmp_path):
        d = str(tmp_path)
        elastic.write_manifest(d, elastic.ResumeManifest(step=4))
        (tmp_path / "MANIFEST").write_text("manifest-999.json\n")  # torn
        assert elastic.latest_manifest(d).step == 4

    def test_empty_directory(self, tmp_path):
        assert elastic.latest_manifest(str(tmp_path)) is None
        assert elastic.manifest_steps(str(tmp_path)) == []


# --------------------------------------------------------------- snapshotter


class TestSnapshotter:
    def test_cadence_and_double_buffer(self, tmp_path):
        snap = elastic.Snapshotter(every=2)
        w = jnp.arange(4.0)
        taken = [s for s in range(1, 7)
                 if snap.maybe(s, {"w": w * s, "s": jnp.int32(s)})]
        assert taken == [2, 4, 6]
        # Async double buffer: the newest snapshot is pending; `latest`
        # commits it and returns the step-6 state.
        step, state = snap.latest
        assert step == 6
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.asarray(w * 6))
        assert snap.stats["snapshots"] == 3
        assert snap.stats["last_ms"] is not None

    def test_window_alignment_enforced(self):
        snap = elastic.Snapshotter(every=10)
        snap.check_alignment(5)  # 10 % 5 == 0: fine
        with pytest.raises(ValueError, match="window"):
            snap.check_alignment(3)

    def test_spill_cadence_and_restore(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=1, spill_every=2)
            template = {"w": jnp.zeros(3)}
            for s in range(1, 5):
                snap.maybe(s, {"w": jnp.arange(3.0) + s},
                           cursor={"offset": s})
            # Snapshots 1-4; every 2nd spills: steps 2 and 4 on disk.
            assert mngr.all_steps() == [2, 4]
            state, manifest = snap.restore(template)
            assert manifest.step == 4 and manifest.cursor["offset"] == 4
            np.testing.assert_array_equal(np.asarray(state["w"]),
                                          np.arange(3.0) + 4)

    def test_flush_is_synchronous_final_snapshot(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100, spill_every=100)
            snap.flush(7, {"w": jnp.float32(3.0)}, cursor=7,
                       rng_key=np.asarray([5, 6], np.uint32))
            assert mngr.all_steps() == [7]
            m = elastic.latest_manifest(str(tmp_path))
            assert m.step == 7 and m.rng_key == [5, 6]

    def test_restore_walks_past_missing_checkpoint(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=1, spill_every=1)
            snap.take(3, {"w": jnp.float32(1.0)}, sync=True)
            # A manifest whose checkpoint never committed (crash between
            # the spill phases) must not wedge the resume.
            elastic.write_manifest(str(tmp_path),
                                   elastic.ResumeManifest(step=9))
            state, manifest = snap.restore({"w": jnp.float32(0.0)})
            assert manifest.step == 3
            assert float(np.asarray(state["w"])) == 1.0

    def test_ram_only_without_manager(self):
        snap = elastic.Snapshotter(every=1)
        snap.take(1, {"w": jnp.float32(1.0)})
        assert snap.restore({"w": jnp.float32(0.0)}) is None
        assert snap.latest[0] == 1


# ------------------------------------------------------------------ signals


class TestPreemptionHandler:
    def test_real_sigterm_sets_flag_only(self):
        with elastic.PreemptionHandler() as handler:
            assert not handler.check()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5
            while not handler.triggered and time.monotonic() < deadline:
                time.sleep(0.01)
            assert handler.triggered and handler.signum == signal.SIGTERM
        # Context exit restored the previous disposition.
        assert signal.getsignal(signal.SIGTERM) != handler._on_signal

    def test_finalize_drains_snapshots_and_exits_preempted(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100)
            handler = elastic.PreemptionHandler(install=False)
            handler.trigger()
            codes = []
            handler.finalize(snap, 5, {"w": jnp.float32(2.0)},
                             _exit=codes.append, cursor={"offset": 20})
            assert codes == [EXIT_PREEMPTED]
            assert mngr.all_steps() == [5]
            assert elastic.latest_manifest(str(tmp_path)).step == 5


# ----------------------------------------------------- exit classification


class TestExitClassification:
    @pytest.mark.parametrize("code,cat", [
        (0, "clean"),
        (2, "usage"),
        (EXIT_PREEMPTED, "preempted"),
        (-signal.SIGTERM, "preempted"),
        (elastic.EXIT_RESIZED, "resized"),
        (1, "crashed"),
        (3, "crashed"),
        (-signal.SIGKILL, "crashed"),
        (-signal.SIGSEGV, "crashed"),
    ])
    def test_classify(self, code, cat):
        assert classify_exit(code) == cat
        assert WorkerExit(0, code).category == cat

    def test_watchdog_kill_classifies_stalled(self):
        """The raw code is the watchdog's SIGKILL; the stalled mark —
        set only by the launcher when ITS watchdog did the killing —
        overrides the would-be 'crashed' classification."""
        assert WorkerExit(1, -signal.SIGKILL, stalled=True).category \
            == "stalled"
        assert WorkerExit(1, -signal.SIGKILL).category == "crashed"

    def test_launch_job_reports_per_rank_codes(self):
        """The satellite contract: worker exit codes propagate
        distinctly instead of collapsing into the kill-all."""
        script = ("import os, sys, time\n"
                  "if os.environ['HOROVOD_RANK'] == '1':\n"
                  f"    sys.exit({EXIT_PREEMPTED})\n"
                  "time.sleep(30)\n")
        result = launch_job([sys.executable, "-c", script], np=2,
                            env=_clean_env())
        assert result.trigger.rank == 1
        assert result.code == EXIT_PREEMPTED
        assert result.category == "preempted"
        # Rank 0 was healthy; its code is the supervisor's SIGTERM, and
        # the per-rank map keeps both distinguishable.
        assert result.exit_codes[1] == EXIT_PREEMPTED
        assert result.exit_codes[0] != EXIT_PREEMPTED
        assert "rank 1" in result.describe()

    def test_launch_job_clean(self):
        result = launch_job([sys.executable, "-c", "pass"], np=2,
                            env=_clean_env())
        assert result.trigger is None and result.category == "clean"
        assert result.exit_codes == {0: 0, 1: 0}

    def test_kill_all_reaps_process_group(self):
        """The kill-all path itself (satellite): TERM -> KILL -> reap,
        bounded."""
        env = _clean_env()
        procs = [_spawn_local(
            [sys.executable, "-c", "import time; time.sleep(60)"], env)
            for _ in range(2)]
        assert all(p.poll() is None for p in procs)
        t0 = time.monotonic()
        _kill_all(procs)
        assert time.monotonic() - t0 < 30
        assert all(p.poll() is not None for p in procs)


# ------------------------------------------------------------ native timeout


class TestNativeTimeout:
    class _StalledLib:
        def hvdtpu_poll(self, handle):
            return 0

        def hvdtpu_rank(self):
            return 3

    class _DoneLib:
        def hvdtpu_poll(self, handle):
            return 1

        def hvdtpu_wait(self, handle):
            return 0

        def hvdtpu_rank(self):
            return 0

    def _core(self, lib, default_timeout=0.0):
        from horovod_tpu.native import NativeCore

        core = NativeCore.__new__(NativeCore)
        core.lib = lib
        core._live = {}
        core._names = {7: "grad.allreduce.bucket0"}
        core._default_timeout = default_timeout
        return core

    def test_stalled_wait_raises_typed_error_with_rank_and_tensor(self):
        core = self._core(self._StalledLib())
        t0 = time.monotonic()
        with pytest.raises(HorovodTimeoutError) as ei:
            core.wait(7, timeout=0.2)
        assert time.monotonic() - t0 < 5  # bounded, never a silent hang
        assert ei.value.rank == 3
        assert ei.value.tensor_name == "grad.allreduce.bucket0"
        assert "grad.allreduce.bucket0" in str(ei.value)
        assert "rank 3" in str(ei.value)

    def test_env_default_timeout_applies(self):
        core = self._core(self._StalledLib(), default_timeout=0.1)
        with pytest.raises(HorovodTimeoutError):
            core.wait(7)  # no explicit timeout: the env default bounds it

    def test_completed_wait_unaffected_by_timeout(self):
        core = self._core(self._DoneLib())
        core.wait(7, timeout=5.0)  # polls true immediately; no error


# --------------------------------------------------------------- supervisor


def _result(codes, trigger=None, pre_kill=None):
    return JobResult(exit_codes=codes, trigger=trigger,
                     pre_kill_codes=pre_kill if pre_kill is not None
                     else ({trigger.rank: trigger.code}
                           if trigger is not None else {}))


class TestSupervisor:
    def _fake_launch(self, outcomes, seen_envs, seen_np=None):
        outcomes = list(outcomes)

        def launch(cmd, np, hosts=None, env=None, jax_distributed=False,
                   **kw):
            seen_envs.append(dict(env or {}))
            if seen_np is not None:
                seen_np.append(np)
            return outcomes.pop(0)

        return launch

    def test_crash_relaunches_then_clean(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1,
            _launch=self._fake_launch([
                _result({0: -9, 1: -15}, WorkerExit(0, -9)),
                _result({0: 0, 1: 0}),
            ], envs))
        assert rc == 0 and len(envs) == 2
        assert envs[0]["HOROVOD_ELASTIC_RESTART"] == "0"
        assert envs[1]["HOROVOD_ELASTIC_RESTART"] == "1"
        assert all(e["HOROVOD_ELASTIC"] == "1" for e in envs)

    def test_crash_budget_exhausted_returns_code(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1,
            _launch=self._fake_launch([
                _result({0: -9}, WorkerExit(0, -9)),
                _result({0: 1}, WorkerExit(0, 1)),
            ], envs))
        assert rc == 1 and len(envs) == 2

    def test_usage_error_never_relaunches(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=5,
            _launch=self._fake_launch(
                [_result({0: 2}, WorkerExit(0, 2))], envs))
        assert rc == EXIT_USAGE and len(envs) == 1

    def test_preemptions_relaunch_for_free(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0,
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: -15}, WorkerExit(0, -15)),
                _result({0: 0}),
            ], envs))
        assert rc == 0 and len(envs) == 3

    def test_count_preemptions_restores_strict_budget(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1, count_preemptions=True,
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
            ], envs))
        assert rc == EXIT_PREEMPTED and len(envs) == 2

    # ------------------------------------------------ resize/shrink/grow

    def test_resize_exit_relaunches_at_plan_size_for_free(self):
        """EXIT_RESIZED on attempt A relaunches at the resize clause's
        n — read supervisor-side from the SAME fault plan — without
        consuming the restart budget."""
        envs, nps = [], []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0, min_np=1,
            env={"HOROVOD_FAULT_PLAN": "resize:rank=0,step=7,n=1"},
            _launch=self._fake_launch([
                _result({0: elastic.EXIT_RESIZED, 1: -15},
                        WorkerExit(0, elastic.EXIT_RESIZED)),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0
        assert nps == [2, 1]
        assert envs[1]["HOROVOD_ELASTIC_RESTART"] == "1"

    def test_resize_out_of_bounds_fails_fast(self):
        with pytest.raises(ValueError, match="bounds"):
            elastic.supervise(
                ["prog"], np=2, max_restarts=0, min_np=1, max_np=2,
                env={"HOROVOD_FAULT_PLAN": "resize:rank=0,step=7,n=5"},
                _launch=self._fake_launch([], []))

    def test_preemption_shrinks_to_survivors(self):
        """With --min-np below the current world, a preemption
        relaunches at np-1 (the reclaimed worker is not coming back)
        instead of burning attempts retrying full size; crashes keep
        the size (the host is still there)."""
        envs, nps = [], []
        rc = elastic.supervise(
            ["prog"], np=3, max_restarts=1, min_np=1,
            _launch=self._fake_launch([
                _result({1: EXIT_PREEMPTED}, WorkerExit(1, EXIT_PREEMPTED)),
                _result({0: -9}, WorkerExit(0, -9)),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0
        assert nps == [3, 2, 2]   # shrink on preempt, hold on crash

    def test_whole_host_loss_shrinks_to_true_survivors(self):
        """Review regression: two ranks reclaimed in the same poll
        (whole-host loss) both appear in pre_kill_codes; the shrink
        removes BOTH, not just the trigger."""
        envs, nps = [], []
        rc = elastic.supervise(
            ["prog"], np=4, max_restarts=0, min_np=1,
            _launch=self._fake_launch([
                _result({2: EXIT_PREEMPTED, 3: EXIT_PREEMPTED},
                        WorkerExit(2, EXIT_PREEMPTED),
                        pre_kill={2: EXIT_PREEMPTED, 3: EXIT_PREEMPTED}),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0 and nps == [4, 2]

    def test_capacity_never_overrides_explicit_resize(self):
        """Review regression: a validated resize: request is the
        operator's word — the slots-file probe must not second-guess
        it on the resize relaunch (it resumes authority afterwards)."""
        envs, nps = [], []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0, min_np=1, max_np=4,
            capacity_fn=lambda: 4,
            env={"HOROVOD_FAULT_PLAN": "resize:rank=0,step=7,n=1"},
            _launch=self._fake_launch([
                _result({0: elastic.EXIT_RESIZED},
                        WorkerExit(0, elastic.EXIT_RESIZED)),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0 and nps == [2, 1]

    def test_metrics_exit_code_is_none_on_exception(self, tmp_path):
        """Review regression: an exception unwinding supervise (^C, a
        launcher crash) must not stamp the metrics record as a clean
        exit-0 run."""
        import json as _json

        path = tmp_path / "metrics.tsv"

        def boom(cmd, np, **kw):
            raise RuntimeError("launcher died")

        with pytest.raises(RuntimeError):
            elastic.supervise(["prog"], np=2, metrics_path=str(path),
                              _launch=boom)
        rec = _json.loads(path.read_text().split("\t", 2)[2])
        assert rec["elastic"]["exit_code"] is None

    def test_fixed_world_without_min_np_never_shrinks(self):
        envs, nps = [], []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0,
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0 and nps == [2, 2]

    def test_capacity_fn_grows_back_when_capacity_returns(self):
        """The capacity probe is the fleet's truth: each relaunch
        clamps to min(available, max_np), so a shrunken world grows
        back on a later restart."""
        envs, nps = [], []
        capacity = iter([1, 4])
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0, min_np=1, max_np=4,
            capacity_fn=lambda: next(capacity),
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: 0}),
            ], envs, nps))
        assert rc == 0
        assert nps == [2, 1, 4]

    def test_slots_file_capacity_reads_and_degrades(self, tmp_path):
        path = tmp_path / "slots"
        fn = elastic.slots_file_capacity(str(path))
        assert fn() is None          # missing: capacity unknown
        path.write_text("3\n")
        assert fn() == 3
        path.write_text("soon\n")
        assert fn() is None          # malformed: keep current size

    def test_stalled_consumes_budget_like_crash(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0,
            _launch=self._fake_launch([
                _result({1: -9}, WorkerExit(1, -9, stalled=True)),
            ], envs))
        assert rc == -9 and len(envs) == 1

    def test_world_bounds_validated(self):
        with pytest.raises(ValueError, match="min_np"):
            elastic.supervise(["prog"], np=2, min_np=3,
                              _launch=self._fake_launch([], []))

    def test_recovery_metrics_json_line(self, tmp_path):
        """The satellite contract: one PERF_RUNS.tsv-format line with
        restarts-by-class, the world trajectory and timings — the input
        tools/perf_summary.py's elastic column renders."""
        import json as _json

        path = tmp_path / "metrics.tsv"
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1, min_np=1,
            metrics_path=str(path),
            env={"HOROVOD_FAULT_PLAN": "resize:rank=0,step=7,n=1"},
            _launch=self._fake_launch([
                _result({0: elastic.EXIT_RESIZED},
                        WorkerExit(0, elastic.EXIT_RESIZED)),
                _result({0: 0}),
            ], envs))
        assert rc == 0
        stamp, lane, payload = \
            path.read_text().strip().split("\t", 2)
        assert lane == "elastic_supervise"
        rec = _json.loads(payload)
        assert rec["value"] == 1 and rec["unit"] == "relaunches"
        e = rec["elastic"]
        assert e["restarts_by_class"] == {"resized": 1}
        assert e["world"] == [2, 1] and e["final_np"] == 1
        # And the perf_summary cell renders it.
        from tools.perf_summary import elastic_cell

        cell = elastic_cell(rec)
        assert "r1" in cell and "2→1" in cell

    def test_heartbeat_dir_namespaced_per_supervisor(self, tmp_path):
        """Regression (round-12 satellite): HOROVOD_HEARTBEAT_DIR is
        exported to workers, so two supervisors sharing one base dir on
        one host used to watch EACH OTHER's hb-<rank> files — a foreign
        rank's touches keep a stalled local rank 'alive' forever. Each
        supervise() must export a unique per-instance subdirectory."""
        base = str(tmp_path / "hb")
        exported = []
        for _ in range(2):
            envs = []
            rc = elastic.supervise(
                ["prog"], np=1, watchdog_timeout=30.0,
                heartbeat_dir=base,
                _launch=self._fake_launch([_result({0: 0})], envs))
            assert rc == 0
            exported.append(envs[0]["HOROVOD_HEARTBEAT_DIR"])
        assert exported[0] != exported[1]
        for d in exported:
            assert os.path.dirname(d) == base
            # ...and each call removed ITS dir on exit: looping over
            # supervise() must not accumulate orphan dirs in the base.
            assert not os.path.exists(d)
        assert os.listdir(base) == []

    def test_disabled_watchdog_drops_inherited_heartbeat_dir(self):
        """With the watchdog off, an INHERITED heartbeat dir (e.g. from
        an outer supervisor) must not be forwarded: this job's workers
        would otherwise touch the outer watchdog's files and mask its
        stall detection."""
        envs = []
        rc = elastic.supervise(
            ["prog"], np=1, watchdog_timeout=0.0,
            env={"HOROVOD_HEARTBEAT_DIR": "/tmp/outer-supervisor-hb"},
            _launch=self._fake_launch([_result({0: 0})], envs))
        assert rc == 0
        assert "HOROVOD_HEARTBEAT_DIR" not in envs[0]

    def test_namespaced_heartbeat_dir_helper_unique(self, tmp_path):
        from horovod_tpu.elastic.signals import namespaced_heartbeat_dir

        a = namespaced_heartbeat_dir(str(tmp_path))
        b = namespaced_heartbeat_dir(str(tmp_path))
        assert a != b and os.path.isdir(a) and os.path.isdir(b)
        assert os.path.dirname(a) == str(tmp_path)
        # no base: a fresh private tempdir, still unique
        c = namespaced_heartbeat_dir(None)
        d = namespaced_heartbeat_dir(None)
        assert c != d and os.path.isdir(c) and os.path.isdir(d)


# ------------------------------------------------------------ resize remap


class TestResizeRemap:
    def _src(self, rank, size, n=512, batch=4):
        return elastic.ShardedBatchSource(
            {"x": np.arange(float(n), dtype=np.float32)},
            batch_size=batch, rank=rank, size=size, seed=0)

    def test_global_stream_is_contiguous_prefix(self):
        """The coverage contract: the union over ranks of one step's
        positions is a contiguous watermark block, so the global stream
        is world-size-independent."""
        for size in (1, 2, 4):
            srcs = [self._src(r, size) for r in range(size)]
            for step in (0, 3, 7):
                union = np.sort(np.concatenate(
                    [s.global_positions(step) for s in srcs]))
                start = srcs[0].consumed_samples(step)
                np.testing.assert_array_equal(
                    union, np.arange(start, start + 4 * size))

    def test_shrink_remap_always_exact(self):
        src2, src1 = self._src(0, 2), self._src(0, 1)
        for step in range(1, 12):
            new = src1.resume_step(src2.cursor(step))
            assert src1.consumed_samples(new) \
                == src2.consumed_samples(step)

    def test_grow_remap_exact_on_even_boundaries(self):
        src2, src4 = self._src(0, 2), self._src(0, 4)
        assert src4.resume_step(src2.cursor(8)) == 4
        with pytest.raises(ValueError, match="global batch"):
            src4.resume_step(src2.cursor(7))   # 56 samples, G_new=16

    def test_remap_accepts_manifest_and_crosses_epochs(self):
        src2 = self._src(0, 2, n=64)   # 8 steps/epoch at size 2
        src1 = self._src(0, 1, n=64)   # 16 steps/epoch at size 1
        m = elastic.ResumeManifest(step=11, world_size=2,
                                   cursor=src2.cursor(11))
        assert src1.resume_step(m) == 22
        # An exact epoch boundary rolls into the next epoch.
        assert src1.resume_step(src2.cursor(8)) == 16

    def test_remap_rejects_cursorless_manifest(self):
        src1 = self._src(0, 1)
        with pytest.raises(ValueError, match="cursor"):
            src1.resume_step(elastic.ResumeManifest(step=5, cursor=5))

    def test_same_world_remap_is_identity(self):
        src = self._src(1, 2)
        assert src.resume_step(src.cursor(9)) == 9

    def test_cross_epoch_remap_rejects_mismatched_epoch_geometry(self):
        """Review regression: past epoch 0, whole epochs must line up
        between the worlds — n=10/B=1 consumes 12 samples/epoch at
        size 3 but 10 at size 2, so a divisible within-epoch offset
        must still be rejected (silent replay otherwise)."""
        src3 = self._src(0, 3, n=10, batch=1)
        src2 = self._src(0, 2, n=10, batch=1)
        cur = src3.cursor(src3.steps_per_epoch + 2)   # epoch 1, off 2
        assert cur["epoch"] == 1
        with pytest.raises(ValueError, match="epoch"):
            src2.resume_step(cur)
        # Epoch 0 of the same geometry pair still remaps fine.
        assert src2.resume_step(src3.cursor(2)) == 3   # g=6 -> step 3

    def test_snapshotter_world_defaults_from_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_RANK", "3")
        monkeypatch.setenv("HOROVOD_SIZE", "4")
        snap = elastic.Snapshotter(every=1)
        assert snap.rank == 3 and snap.world_size == 4


# ---------------------------------------------------------- reshard resume


class TestReshardResume:
    """The Snapshotter/loop world-size-mismatch behavior: what used to
    be an implicit dead end is now the reshard path — a mismatched
    manifest resumes through the cursor remap + on_resize hook, and
    only a remap-less resume is rejected (with the reshard pointer)."""

    def _train(self, tmp_path, src, steps, world_size, **kw):
        def step_fn(state, batch):
            g = jnp.mean(batch["x"])
            return ({"w": state["w"] - 0.01 * g,
                     "step": state["step"] + 1},
                    {"loss": state["w"]})

        init = {"w": jnp.float32(2.0), "step": jnp.int32(0)}
        m = CheckpointManager(str(tmp_path), backend="numpy")
        return elastic.run_elastic(
            step_fn, init, src.batch_at if src is not None
            else (lambda s: {"x": jnp.float32(s)}),
            steps, manager=m, snapshot_every=3,
            world_size=world_size, rank=0, **kw)

    def test_reshard_resume_remaps_and_rescales(self, tmp_path):
        arrays = {"x": np.arange(64, dtype=np.float32)}
        src2 = elastic.ShardedBatchSource(arrays, batch_size=4, rank=0,
                                          size=2, seed=0)
        self._train(tmp_path, src2, 6, 2)     # manifest: step 6 @ world 2
        m = elastic.latest_manifest(str(tmp_path))
        assert m.step == 6 and m.world_size == 2
        assert m.cursor["size"] == 2          # source cursor recorded

        src1 = elastic.ShardedBatchSource(arrays, batch_size=4, rank=0,
                                          size=1, seed=0)
        resizes = []

        def on_resize(old, new, state):
            resizes.append((old, new))
            return dict(state, w=state["w"] * 2)

        state, _, resumed = self._train(tmp_path, src1, 24, 1,
                                        on_resize=on_resize)
        # 6 steps @ world 2 = 48 samples = 12 steps @ world 1; the
        # default remap came from the batch source itself.
        assert resumed == 12
        assert resizes == [(2, 1)]
        # The resized run wrote a world-1 manifest at its end.
        assert elastic.latest_manifest(str(tmp_path)).world_size == 1

    def test_mismatch_without_remap_is_rejected_with_pointer(
            self, tmp_path):
        arrays = {"x": np.arange(64, dtype=np.float32)}
        src2 = elastic.ShardedBatchSource(arrays, batch_size=4, rank=0,
                                          size=2, seed=0)
        self._train(tmp_path, src2, 6, 2)
        with pytest.raises(ValueError, match="reshard"):
            self._train(tmp_path, None, 24, 1)

    def test_resume_manager_is_the_restore_authority(self, tmp_path):
        """A rank with no history of its own (a grown world's new rank)
        restores from the authority directory while spilling to its
        own."""
        step_fn, batch_for, init = _toy_step()
        auth = CheckpointManager(str(tmp_path / "rank0"), backend="numpy")
        elastic.run_elastic(step_fn, init, batch_for, 6, manager=auth,
                            snapshot_every=3, world_size=1, rank=0)
        own = CheckpointManager(str(tmp_path / "rank2"), backend="numpy")
        s, _, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=own, snapshot_every=3,
            world_size=1, rank=2,
            resume_manager=CheckpointManager(str(tmp_path / "rank0"),
                                             backend="numpy"))
        assert resumed == 6
        # ... and its own spills landed in its own directory.
        assert elastic.latest_manifest(str(tmp_path / "rank2")).step == 12

    def test_heartbeat_touched_at_boundaries(self, tmp_path,
                                             monkeypatch):
        monkeypatch.setenv("HOROVOD_HEARTBEAT_DIR", str(tmp_path / "hb"))
        step_fn, batch_for, init = _toy_step()
        elastic.run_elastic(step_fn, init, batch_for, 4,
                            snapshot_every=2)
        hb = tmp_path / "hb" / "hb-0"
        assert hb.exists()
        assert hb.read_text().split()[1] == "4"   # last boundary stamped


# --------------------------------------------------------------- watchdog


class TestHealthWatchdog:
    def test_stale_detection_and_throttle(self, tmp_path):
        from horovod_tpu.elastic.signals import Heartbeat

        hb = Heartbeat(str(tmp_path), rank=0)
        hb.touch(3)
        os.utime(hb.path, (time.time() - 10, time.time() - 10))
        wd = elastic.HealthWatchdog(str(tmp_path), timeout=2.0,
                                    interval=0.0)
        stale = wd.check([0, 1])
        assert set(stale) == {0} and stale[0] > 2.0   # rank 1: no file
        wd.kills[0] = stale[0]
        assert wd.check([0, 1]) == {}                 # already killed
        wd.reset()
        assert set(wd.check([0])) == {0}              # re-armed

    def test_fresh_heartbeat_not_stale(self, tmp_path):
        from horovod_tpu.elastic.signals import Heartbeat

        Heartbeat(str(tmp_path), rank=0).touch(1)
        wd = elastic.HealthWatchdog(str(tmp_path), timeout=30.0,
                                    interval=0.0)
        assert wd.check([0]) == {}

    def test_launch_job_kills_stalled_worker(self, tmp_path):
        """The integration contract: a worker that beats once then goes
        silent is killed by the watchdog riding the supervision poll,
        and the incident is classified *stalled* (with the observed
        heartbeat age as time-to-detect evidence)."""
        hb_dir = tmp_path / "hb"
        hb_dir.mkdir()
        script = (
            "import os, time\n"
            "rank = os.environ['HOROVOD_RANK']\n"
            "if rank == '0':\n"
            "    open(os.path.join(os.environ['HOROVOD_HEARTBEAT_DIR'],"
            " 'hb-0'), 'w').write('0')\n"
            "time.sleep(60)\n")
        env = _clean_env()
        env["HOROVOD_HEARTBEAT_DIR"] = str(hb_dir)
        wd = elastic.HealthWatchdog(str(hb_dir), timeout=1.0,
                                    interval=0.1)
        t0 = time.monotonic()
        result = launch_job([sys.executable, "-c", script], np=2,
                            env=env, watchdog=wd)
        assert time.monotonic() - t0 < 30
        assert result.trigger.rank == 0 and result.trigger.stalled
        assert result.category == "stalled"
        assert result.stalled_ranks[0] > 1.0


# ------------------------------------------------------------- elastic loop


class TestRunElastic:
    def test_resume_is_bit_exact_plain(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, met_full, r0 = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3)
        assert r0 == 0
        # Interrupted run: 6 steps, then a fresh invocation to 12 —
        # exactly what a relaunch does.
        m = CheckpointManager(str(tmp_path / "ckpt"), backend="numpy")
        _, met_a, _ = elastic.run_elastic(
            step_fn, init, batch_for, 6, manager=m, snapshot_every=3)
        s_b, met_b, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m, snapshot_every=3)
        assert resumed == 6
        assert float(np.asarray(s_b["w"])) == float(np.asarray(s_full["w"]))
        traj_full = {s: float(m_["loss"]) for s, m_ in met_full}
        traj_ab = {s: float(m_["loss"]) for s, m_ in met_a + met_b}
        assert traj_ab == traj_full  # identical loss trajectory

    def test_resume_is_bit_exact_windowed(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, met_full, _ = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3, steps_per_dispatch=3)
        m = CheckpointManager(str(tmp_path / "ckpt"), backend="numpy")
        elastic.run_elastic(step_fn, init, batch_for, 6, manager=m,
                            snapshot_every=3, steps_per_dispatch=3)
        s_b, met_b, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m,
            snapshot_every=3, steps_per_dispatch=3)
        assert resumed == 6
        assert float(np.asarray(s_b["w"])) == float(np.asarray(s_full["w"]))
        # Window metric means replay identically too.
        full = {s: float(m_["loss"]) for s, m_ in met_full}
        replay = {s: float(m_["loss"]) for s, m_ in met_b}
        for s, v in replay.items():
            assert full[s] == v

    def test_finished_run_reinvocation_is_noop_resume(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m = CheckpointManager(str(tmp_path), backend="numpy")
        s1, _, _ = elastic.run_elastic(step_fn, init, batch_for, 6,
                                       manager=m, snapshot_every=3)
        s2, met2, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 6, manager=m, snapshot_every=3)
        assert resumed == 6 and met2 == []
        assert float(np.asarray(s2["w"])) == float(np.asarray(s1["w"]))

    def test_preemption_at_boundary_saves_and_exits_75(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m = CheckpointManager(str(tmp_path), backend="numpy")
        handler = elastic.PreemptionHandler(install=False)
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("preempt:rank=0,step=4"),
            rank=0, attempt=0)
        with pytest.raises(SystemExit) as ei:
            elastic.run_elastic(step_fn, init, batch_for, 12, manager=m,
                                snapshot_every=2, injector=inj,
                                preemption=handler)
        assert ei.value.code == EXIT_PREEMPTED
        manifest = elastic.latest_manifest(str(tmp_path))
        assert manifest.step == 4  # drained + snapshotted at the boundary
        # And the relaunch resumes exactly there, to the same final state.
        s_resumed, _, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m, snapshot_every=2)
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, _, _ = elastic.run_elastic(step_fn, init, batch_for, 12,
                                           manager=m_full, snapshot_every=2)
        assert resumed == 4
        assert float(np.asarray(s_resumed["w"])) == \
            float(np.asarray(s_full["w"]))

    def test_sharded_batch_source_cursor(self):
        root = np.random.RandomState(0)
        src = elastic.ShardedBatchSource(
            {"x": root.normal(size=(40, 2)).astype(np.float32)},
            batch_size=4, rank=1, size=2, seed=3)
        assert src.steps_per_epoch == 5
        cur = src.cursor(7)
        assert cur == {"epoch": 1, "offset": 8, "rank": 1, "size": 2}
        # Deterministic in the step — the whole resume argument.
        np.testing.assert_array_equal(src.batch_at(7)["x"],
                                      src.batch_at(7)["x"])
        # Disjoint from the other rank's shard at the same step.
        other = elastic.ShardedBatchSource(
            {"x": src.arrays["x"]}, batch_size=4, rank=0, size=2, seed=3)
        assert not np.array_equal(src.batch_at(0)["x"],
                                  other.batch_at(0)["x"])

    def test_prebuilt_snapshotter_resumes_too(self, tmp_path):
        """The composable path — run_elastic(snapshotter=Snapshotter(
        manager=...)) with no manager kwarg — must resume and final-
        flush exactly like the manager kwarg path (review finding: the
        gates used to check the kwarg only)."""
        step_fn, batch_for, init = _toy_step()
        mngr = CheckpointManager(str(tmp_path), backend="numpy")
        elastic.run_elastic(
            step_fn, init, batch_for, 6,
            snapshotter=elastic.Snapshotter(mngr, every=3))
        assert elastic.latest_manifest(str(tmp_path)).step == 6
        s2, _, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12,
            snapshotter=elastic.Snapshotter(mngr, every=3))
        assert resumed == 6
        m_full = CheckpointManager(str(tmp_path / "full"),
                                   backend="numpy")
        s_full, _, _ = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3)
        assert float(np.asarray(s2["w"])) == float(np.asarray(s_full["w"]))

    def test_flush_with_state_requires_step(self):
        snap = elastic.Snapshotter(every=1)
        with pytest.raises(ValueError, match="step"):
            snap.flush(state={"w": jnp.float32(1.0)})

    def test_misaligned_cadence_rejected(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        with pytest.raises(ValueError, match="window"):
            elastic.run_elastic(
                step_fn, init, batch_for, 12,
                manager=CheckpointManager(str(tmp_path), backend="numpy"),
                snapshot_every=4, steps_per_dispatch=3)


# ------------------------------------------------------------ flax binding


class TestElasticSnapshotCallback:
    def _loop_pieces(self):
        import horovod_tpu.flax as hvd_flax

        def step_fn(state, batch):
            return ({"w": state["w"] - 0.1 * batch["x"],
                     "step": state["step"] + 1},
                    {"loss": jnp.sum(state["w"])})

        def data_fn(epoch):
            for i in range(4):
                yield {"x": jnp.float32(i + 1)}

        init = {"w": jnp.float32(1.0), "step": jnp.int32(0)}
        return hvd_flax, step_fn, data_fn, init

    def test_cadence_snapshots_and_final_flush(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("HOROVOD_HEARTBEAT_DIR",
                           str(tmp_path / "hb"))
        hvd_flax, step_fn, data_fn, init = self._loop_pieces()
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=4, spill_every=1)
            loop = hvd_flax.TrainLoop(
                init, step_fn, data_fn,
                callbacks=[hvd_flax.ElasticSnapshotCallback(snap)])
            loop.fit(epochs=2)  # 8 steps: cadence spill at 4, flush at 8
            assert mngr.all_steps() == [4, 8]
            # The keras-lane face feeds the watchdog too: the per-rank
            # heartbeat was touched at every batch boundary.
            assert (tmp_path / "hb" / "hb-0").exists()
            restored, manifest = snap.restore(init)
            assert manifest.step == 8
            np.testing.assert_array_equal(np.asarray(restored["w"]),
                                          np.asarray(loop.state["w"]))

    def test_preemption_mid_fit_saves_and_exits(self, tmp_path):
        hvd_flax, step_fn, data_fn, init = self._loop_pieces()
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100)
            handler = elastic.PreemptionHandler(install=False)

            class TriggerAtStep3(hvd_flax.Callback):
                def on_batch_end(self, batch, logs=None):
                    if int(self.loop.state["step"]) == 3:
                        handler.trigger()

            loop = hvd_flax.TrainLoop(
                init, step_fn, data_fn,
                callbacks=[TriggerAtStep3(),
                           hvd_flax.ElasticSnapshotCallback(
                               snap, preemption=handler)])
            with pytest.raises(SystemExit) as ei:
                loop.fit(epochs=2)
            assert ei.value.code == EXIT_PREEMPTED
            assert elastic.latest_manifest(str(tmp_path)).step == 3


# ------------------------------------------------------------------- e2e


def _last_wins(path: Path) -> dict:
    out = {}
    for line in path.read_text().splitlines():
        step, value = line.split()
        out[int(step)] = value
    return out


def _run_elastic_job(tmp_path, tag, steps, every, k, fault=None,
                     expect_rc=0, env_extra=None):
    out = tmp_path / f"{tag}-out"
    ckpt = tmp_path / f"{tag}-ckpt"
    cmd = [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
           "--elastic", "--max-restarts", "1"]
    if fault:
        cmd += ["--fault-plan", fault]
    cmd += [sys.executable, str(REPO / "tests" / "elastic_worker.py"),
            str(out), str(ckpt), str(steps), str(every), str(k)]
    env = _clean_env()
    env.update(env_extra or {})
    proc = subprocess.run(cmd, env=env, cwd=str(REPO),
                          timeout=600, capture_output=True, text=True)
    assert proc.returncode == expect_rc, (proc.stdout[-2000:],
                                          proc.stderr[-2000:])
    return out, proc


def _run_resize_job(tmp_path, tag, total_samples, np_, fault,
                    min_np=1, max_np=None, every=4, k=1):
    out = tmp_path / f"{tag}-out"
    ckpt = tmp_path / f"{tag}-ckpt"
    cmd = [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_),
           "--elastic", "--max-restarts", "1", "--min-np", str(min_np)]
    if max_np is not None:
        cmd += ["--max-np", str(max_np)]
    cmd += ["--fault-plan", fault,
            sys.executable,
            str(REPO / "tests" / "elastic_resize_worker.py"),
            str(out), str(ckpt), str(total_samples), str(every), str(k)]
    proc = subprocess.run(cmd, env=_clean_env(), cwd=str(REPO),
                          timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    return out, proc


def _check_sample_coverage(samples_path: Path, total_samples: int,
                           n=512, batch=4, seed=0):
    """Replay rank 0's lineage and assert the no-drop/no-duplicate
    contract: at each attempt, entries at or past the attempt's resume
    watermark belong to a discarded lineage; what remains must cover
    the global permutation prefix exactly once."""
    attempts = {}
    for line in samples_path.read_text().splitlines():
        parts = line.split()
        if parts[0] != "S":
            continue
        a, size, step, watermark = map(int, parts[1:5])
        ids = [int(x) for x in parts[5:]]
        attempts.setdefault(a, []).append((watermark, size, ids))
    assert attempts, "no sample log lines"
    consumed = {}   # dataset id -> watermark of the consuming step
    for a in sorted(attempts):
        w0 = min(w for w, _, _ in attempts[a])
        for id_, w in list(consumed.items()):
            if w >= w0:
                del consumed[id_]   # discarded lineage
        for w, size, ids in sorted(attempts[a]):
            assert len(ids) == batch * size
            for id_ in ids:
                assert id_ not in consumed, \
                    f"sample {id_} consumed twice (at {consumed[id_]} " \
                    f"and {w})"
                consumed[id_] = w
    final = attempts[max(attempts)]
    final_w = max(w + len(ids) for w, _, ids in final)
    assert final_w == total_samples
    assert len(consumed) == total_samples
    # The consumed ids ARE the world-independent global stream: the
    # seeded epoch permutation's prefix (single epoch by construction).
    from horovod_tpu.data.sharding import shard_indices

    assert total_samples <= n
    stream = shard_indices(n, epoch=0, rank=0, size=1, shuffle=True,
                           seed=seed)[:total_samples]
    assert set(consumed) == {int(x) for x in stream}


class TestEndToEnd:
    """Acceptance: `hvdrun --elastic --max-restarts 1` with a fault plan
    killing rank 1 mid-run resumes from the snapshot and finishes with a
    bit-exact final state and loss trajectory vs. the fault-free run."""

    @pytest.mark.parametrize("k", [1, 3])
    def test_kill_rank1_resumes_bit_exact(self, tmp_path, k):
        steps, every = 18, 3
        clean_out, _ = _run_elastic_job(tmp_path, f"clean{k}", steps,
                                        every, k)
        fault_out, proc = _run_elastic_job(
            tmp_path, f"fault{k}", steps, every, k,
            fault="kill:rank=1,step=7")
        # The supervisor actually classified the SIGKILL and relaunched.
        assert "crashed" in proc.stderr
        assert "relaunching all 2 rank(s)" in proc.stderr
        for rank in (0, 1):
            clean_final = (clean_out / f"rank{rank}.final").read_text()
            fault_final = (fault_out / f"rank{rank}.final").read_text()
            # Same weights bit-for-bit (the digest covers every leaf).
            assert clean_final.split()[0] == fault_final.split()[0]
            # The interrupted+resumed trajectory equals the fault-free
            # one at every step it recorded (repr equality = bit-exact).
            clean_traj = _last_wins(clean_out / f"rank{rank}.traj")
            fault_traj = _last_wins(fault_out / f"rank{rank}.traj")
            assert fault_traj == clean_traj
        # The killed rank really did resume from a mid-run snapshot.
        assert "resumed=0" not in (fault_out / "rank1.final").read_text()

    def test_malformed_fault_plan_is_usage_error(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run", "-np", "1",
             "--elastic", "--fault-plan", "explode:rank=0",
             sys.executable, "-c", "pass"],
            env=_clean_env(), cwd=str(REPO), timeout=120,
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "fault plan" in proc.stderr

    def test_resize_outside_world_bounds_is_usage_error(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
             "--elastic", "--fault-plan", "resize:rank=0,step=7,n=1",
             sys.executable, "-c", "pass"],   # no --min-np: bounds [2,2]
            env=_clean_env(), cwd=str(REPO), timeout=120,
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "bounds" in proc.stderr

    def test_stall_fault_terminates_via_watchdog(self, tmp_path):
        """The acceptance gap this PR closes: a stall: fault with no
        secs (= hang forever) used to wedge the job until
        HOROVOD_NEGOTIATION_TIMEOUT (default: forever). The heartbeat
        watchdog now kills the silent rank, classifies the incident
        *stalled*, and the relaunch finishes the run."""
        out, proc = _run_elastic_job(
            tmp_path, "stall", 18, 3, 1,
            fault="stall:rank=1,step=5",
            env_extra={"HOROVOD_WATCHDOG_TIMEOUT": "2"})
        assert "health watchdog" in proc.stderr
        assert "stalled" in proc.stderr
        assert "relaunching" in proc.stderr
        # Both ranks finished after the relaunch; rank 1 resumed from a
        # mid-run snapshot rather than restarting cold.
        for rank in (0, 1):
            assert (out / f"rank{rank}.final").exists()
        assert "resumed=0" not in (out / "rank1.final").read_text()


class TestEndToEndResize:
    """The resize acceptance path: `hvdrun --elastic --min-np 1 -np 2
    --fault-plan "resize:rank=0,step=7,n=1"` shrinks to np=1, resumes
    from the manifest through the cursor remap, finishes, and every
    global sample index is consumed exactly once across the resize —
    plus run-determinism given the same resize schedule, and the
    slow-marked full shrink/grow matrix."""

    TOTAL = 128   # global samples: 16 steps @ np2, 32 @ np1, 8 @ np4

    def test_shrink_2_to_1_coverage(self, tmp_path):
        fault = "resize:rank=0,step=7,n=1"
        out_a, proc = _run_resize_job(tmp_path, "shrink-a", self.TOTAL,
                                      2, fault)
        assert "resized" in proc.stderr
        assert "resizing world 2 -> 1" in proc.stderr
        # The worker really went through the reshard remap: 7 steps @
        # world 2 = 56 samples = step 14 @ world 1.
        final = (out_a / "rank0.final").read_text()
        assert "resumed=14" in final
        # The LR rescale hook fired on the world change.
        assert any(line.startswith("Z 2 1 ")
                   for line in (out_a / "rank0.samples")
                   .read_text().splitlines())
        _check_sample_coverage(out_a / "rank0.samples", self.TOTAL)

    @pytest.mark.slow
    def test_shrink_determinism_given_same_schedule(self, tmp_path):
        """Two identical resize schedules reproduce the trajectory, the
        sample stream and the final state bit-for-bit (RNG folding and
        the cursor remap are pure functions of (step, rank, world))."""
        fault = "resize:rank=0,step=7,n=1"
        out_a, _ = _run_resize_job(tmp_path, "det-a", self.TOTAL,
                                   2, fault)
        out_b, _ = _run_resize_job(tmp_path, "det-b", self.TOTAL,
                                   2, fault)
        for name in ("rank0.traj", "rank0.samples", "rank0.final"):
            assert (out_a / name).read_text() \
                == (out_b / name).read_text(), name

    @pytest.mark.slow
    def test_shrink_4_to_2_coverage(self, tmp_path):
        out, proc = _run_resize_job(
            tmp_path, "shrink42", self.TOTAL, 4,
            "resize:rank=0,step=6,n=2")
        assert "resizing world 4 -> 2" in proc.stderr
        # 6 steps @ world 4 = 96 samples = step 12 @ world 2.
        assert "resumed=12" in (out / "rank0.final").read_text()
        _check_sample_coverage(out / "rank0.samples", self.TOTAL)

    @pytest.mark.slow
    def test_grow_2_to_4_coverage(self, tmp_path):
        out, proc = _run_resize_job(
            tmp_path, "grow24", self.TOTAL, 2,
            "resize:rank=0,step=8,n=4", max_np=4)
        assert "resizing world 2 -> 4" in proc.stderr
        # 8 steps @ world 2 = 64 samples = step 4 @ world 4; the grown
        # world's brand-new ranks restored from rank 0's manifest.
        for rank in range(4):
            final = out / f"rank{rank}.final"
            assert final.exists()
            assert "resumed=4" in final.read_text()
        _check_sample_coverage(out / "rank0.samples", self.TOTAL)
