"""horovod_tpu.elastic: snapshots, manifests, signals, fault injection,
exit-code classification, supervised restart — and the end-to-end
acceptance path: a fault-injected `hvdrun --elastic` job that loses a
rank mid-run and still finishes bit-exactly equal to the fault-free run.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import elastic
from horovod_tpu.common.exceptions import HorovodTimeoutError
from horovod_tpu.elastic.faults import FaultPlanError
from horovod_tpu.flax.checkpoint import CheckpointManager
from horovod_tpu.run import (JobResult, WorkerExit, classify_exit,
                             launch_job, _kill_all, _spawn_local)
from horovod_tpu.run.driver import EXIT_PREEMPTED, EXIT_USAGE

REPO = Path(__file__).resolve().parent.parent


def _clean_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("HOROVOD_FAULT_PLAN", None)
    return env


# ----------------------------------------------------------------- fixtures


def _toy_step():
    def step_fn(state, batch):
        g = batch["x"] * state["w"]
        return ({"w": state["w"] - 0.1 * g, "step": state["step"] + 1},
                {"loss": jnp.sum(state["w"])})

    def batch_for(step):
        return {"x": jnp.float32(step % 5 + 1)}

    init = {"w": jnp.float32(2.0), "step": jnp.int32(0)}
    return step_fn, batch_for, init


# ---------------------------------------------------------------- FaultPlan


class TestFaultPlan:
    def test_parse_grammar(self):
        plan = elastic.parse_fault_plan(
            "kill:rank=1,step=7; stall:rank=2,step=12,secs=0.5;"
            "preempt:rank=0,step=3,attempt=1;exit:rank=0,step=2,code=9")
        kinds = [a.kind for a in plan]
        assert kinds == ["kill", "stall", "preempt", "exit"]
        assert plan[0].rank == 1 and plan[0].step == 7
        assert plan[0].attempt == 0  # default: first launch only
        assert plan[1].secs == 0.5
        assert plan[2].attempt == 1
        assert plan[3].code == 9
        assert elastic.parse_fault_plan("") == []
        assert elastic.parse_fault_plan("  ;  ") == []

    @pytest.mark.parametrize("bad", [
        "explode:rank=0,step=1",          # unknown kind
        "kill:rank=0",                    # missing step
        "kill:step=3",                    # missing rank
        "kill:rank=zero,step=1",          # non-numeric
        "kill:rank=0,step=1,flavor=spicy",  # unknown key
        "kill rank=0 step=1",             # no colon
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(FaultPlanError):
            elastic.parse_fault_plan(bad)

    def test_injector_filters_rank_and_attempt(self):
        plan = elastic.parse_fault_plan(
            "exit:rank=0,step=5;exit:rank=1,step=5;"
            "exit:rank=0,step=9,attempt=1")
        inj = elastic.FaultInjector(plan, rank=0, attempt=1)
        assert [a.step for a in inj.pending] == [9]
        inj0 = elastic.FaultInjector(plan, rank=1, attempt=0)
        assert [a.step for a in inj0.pending] == [5]

    def test_exit_action_fires_once_at_boundary(self):
        plan = elastic.parse_fault_plan("exit:rank=0,step=5,code=7")
        inj = elastic.FaultInjector(plan, rank=0, attempt=0)
        inj.maybe_inject(4)  # below the step: nothing
        with pytest.raises(SystemExit) as ei:
            inj.maybe_inject(6)  # first boundary past step=5
        assert ei.value.code == 7
        inj.maybe_inject(7)  # consumed: does not re-fire

    def test_stall_action_sleeps_bounded(self):
        plan = elastic.parse_fault_plan("stall:rank=0,step=1,secs=0.2")
        inj = elastic.FaultInjector(plan, rank=0, attempt=0)
        t0 = time.monotonic()
        inj.maybe_inject(1)
        assert 0.15 <= time.monotonic() - t0 < 5.0

    def test_preempt_action_triggers_handler_not_signal(self):
        handler = elastic.PreemptionHandler(install=False)
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("preempt:rank=0,step=2"),
            rank=0, attempt=0)
        inj.maybe_inject(2, preemption=handler)
        assert handler.triggered

    def test_env_construction(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_FAULT_PLAN", "kill:rank=3,step=11")
        monkeypatch.setenv("HOROVOD_RANK", "3")
        monkeypatch.setenv("HOROVOD_ELASTIC_RESTART", "0")
        inj = elastic.FaultInjector.from_env()
        assert [a.kind for a in inj.pending] == ["kill"]


# ----------------------------------------------------------------- manifest


class TestManifest:
    def test_round_trip_and_latest(self, tmp_path):
        d = str(tmp_path)
        m1 = elastic.ResumeManifest(step=3, world_size=2, rank=0,
                                    cursor={"epoch": 0, "offset": 12},
                                    rng_key=[1, 2])
        m2 = elastic.ResumeManifest(step=6, world_size=2, rank=0,
                                    cursor={"epoch": 0, "offset": 24})
        elastic.write_manifest(d, m1)
        elastic.write_manifest(d, m2)
        assert elastic.manifest_steps(d) == [3, 6]
        latest = elastic.latest_manifest(d)
        assert latest.step == 6 and latest.cursor["offset"] == 24
        old = elastic.read_manifest(d, 3)
        assert old.rng_key == [1, 2]
        assert np.array_equal(old.rng(), np.asarray([1, 2], np.uint32))

    def test_latest_survives_torn_pointer(self, tmp_path):
        d = str(tmp_path)
        elastic.write_manifest(d, elastic.ResumeManifest(step=4))
        (tmp_path / "MANIFEST").write_text("manifest-999.json\n")  # torn
        assert elastic.latest_manifest(d).step == 4

    def test_empty_directory(self, tmp_path):
        assert elastic.latest_manifest(str(tmp_path)) is None
        assert elastic.manifest_steps(str(tmp_path)) == []


# --------------------------------------------------------------- snapshotter


class TestSnapshotter:
    def test_cadence_and_double_buffer(self, tmp_path):
        snap = elastic.Snapshotter(every=2)
        w = jnp.arange(4.0)
        taken = [s for s in range(1, 7)
                 if snap.maybe(s, {"w": w * s, "s": jnp.int32(s)})]
        assert taken == [2, 4, 6]
        # Async double buffer: the newest snapshot is pending; `latest`
        # commits it and returns the step-6 state.
        step, state = snap.latest
        assert step == 6
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.asarray(w * 6))
        assert snap.stats["snapshots"] == 3
        assert snap.stats["last_ms"] is not None

    def test_window_alignment_enforced(self):
        snap = elastic.Snapshotter(every=10)
        snap.check_alignment(5)  # 10 % 5 == 0: fine
        with pytest.raises(ValueError, match="window"):
            snap.check_alignment(3)

    def test_spill_cadence_and_restore(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=1, spill_every=2)
            template = {"w": jnp.zeros(3)}
            for s in range(1, 5):
                snap.maybe(s, {"w": jnp.arange(3.0) + s},
                           cursor={"offset": s})
            # Snapshots 1-4; every 2nd spills: steps 2 and 4 on disk.
            assert mngr.all_steps() == [2, 4]
            state, manifest = snap.restore(template)
            assert manifest.step == 4 and manifest.cursor["offset"] == 4
            np.testing.assert_array_equal(np.asarray(state["w"]),
                                          np.arange(3.0) + 4)

    def test_flush_is_synchronous_final_snapshot(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100, spill_every=100)
            snap.flush(7, {"w": jnp.float32(3.0)}, cursor=7,
                       rng_key=np.asarray([5, 6], np.uint32))
            assert mngr.all_steps() == [7]
            m = elastic.latest_manifest(str(tmp_path))
            assert m.step == 7 and m.rng_key == [5, 6]

    def test_restore_walks_past_missing_checkpoint(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=1, spill_every=1)
            snap.take(3, {"w": jnp.float32(1.0)}, sync=True)
            # A manifest whose checkpoint never committed (crash between
            # the spill phases) must not wedge the resume.
            elastic.write_manifest(str(tmp_path),
                                   elastic.ResumeManifest(step=9))
            state, manifest = snap.restore({"w": jnp.float32(0.0)})
            assert manifest.step == 3
            assert float(np.asarray(state["w"])) == 1.0

    def test_ram_only_without_manager(self):
        snap = elastic.Snapshotter(every=1)
        snap.take(1, {"w": jnp.float32(1.0)})
        assert snap.restore({"w": jnp.float32(0.0)}) is None
        assert snap.latest[0] == 1


# ------------------------------------------------------------------ signals


class TestPreemptionHandler:
    def test_real_sigterm_sets_flag_only(self):
        with elastic.PreemptionHandler() as handler:
            assert not handler.check()
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5
            while not handler.triggered and time.monotonic() < deadline:
                time.sleep(0.01)
            assert handler.triggered and handler.signum == signal.SIGTERM
        # Context exit restored the previous disposition.
        assert signal.getsignal(signal.SIGTERM) != handler._on_signal

    def test_finalize_drains_snapshots_and_exits_preempted(self, tmp_path):
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100)
            handler = elastic.PreemptionHandler(install=False)
            handler.trigger()
            codes = []
            handler.finalize(snap, 5, {"w": jnp.float32(2.0)},
                             _exit=codes.append, cursor={"offset": 20})
            assert codes == [EXIT_PREEMPTED]
            assert mngr.all_steps() == [5]
            assert elastic.latest_manifest(str(tmp_path)).step == 5


# ----------------------------------------------------- exit classification


class TestExitClassification:
    @pytest.mark.parametrize("code,cat", [
        (0, "clean"),
        (2, "usage"),
        (EXIT_PREEMPTED, "preempted"),
        (-signal.SIGTERM, "preempted"),
        (1, "crashed"),
        (3, "crashed"),
        (-signal.SIGKILL, "crashed"),
        (-signal.SIGSEGV, "crashed"),
    ])
    def test_classify(self, code, cat):
        assert classify_exit(code) == cat
        assert WorkerExit(0, code).category == cat

    def test_launch_job_reports_per_rank_codes(self):
        """The satellite contract: worker exit codes propagate
        distinctly instead of collapsing into the kill-all."""
        script = ("import os, sys, time\n"
                  "if os.environ['HOROVOD_RANK'] == '1':\n"
                  f"    sys.exit({EXIT_PREEMPTED})\n"
                  "time.sleep(30)\n")
        result = launch_job([sys.executable, "-c", script], np=2,
                            env=_clean_env())
        assert result.trigger.rank == 1
        assert result.code == EXIT_PREEMPTED
        assert result.category == "preempted"
        # Rank 0 was healthy; its code is the supervisor's SIGTERM, and
        # the per-rank map keeps both distinguishable.
        assert result.exit_codes[1] == EXIT_PREEMPTED
        assert result.exit_codes[0] != EXIT_PREEMPTED
        assert "rank 1" in result.describe()

    def test_launch_job_clean(self):
        result = launch_job([sys.executable, "-c", "pass"], np=2,
                            env=_clean_env())
        assert result.trigger is None and result.category == "clean"
        assert result.exit_codes == {0: 0, 1: 0}

    def test_kill_all_reaps_process_group(self):
        """The kill-all path itself (satellite): TERM -> KILL -> reap,
        bounded."""
        env = _clean_env()
        procs = [_spawn_local(
            [sys.executable, "-c", "import time; time.sleep(60)"], env)
            for _ in range(2)]
        assert all(p.poll() is None for p in procs)
        t0 = time.monotonic()
        _kill_all(procs)
        assert time.monotonic() - t0 < 30
        assert all(p.poll() is not None for p in procs)


# ------------------------------------------------------------ native timeout


class TestNativeTimeout:
    class _StalledLib:
        def hvdtpu_poll(self, handle):
            return 0

        def hvdtpu_rank(self):
            return 3

    class _DoneLib:
        def hvdtpu_poll(self, handle):
            return 1

        def hvdtpu_wait(self, handle):
            return 0

        def hvdtpu_rank(self):
            return 0

    def _core(self, lib, default_timeout=0.0):
        from horovod_tpu.native import NativeCore

        core = NativeCore.__new__(NativeCore)
        core.lib = lib
        core._live = {}
        core._names = {7: "grad.allreduce.bucket0"}
        core._default_timeout = default_timeout
        return core

    def test_stalled_wait_raises_typed_error_with_rank_and_tensor(self):
        core = self._core(self._StalledLib())
        t0 = time.monotonic()
        with pytest.raises(HorovodTimeoutError) as ei:
            core.wait(7, timeout=0.2)
        assert time.monotonic() - t0 < 5  # bounded, never a silent hang
        assert ei.value.rank == 3
        assert ei.value.tensor_name == "grad.allreduce.bucket0"
        assert "grad.allreduce.bucket0" in str(ei.value)
        assert "rank 3" in str(ei.value)

    def test_env_default_timeout_applies(self):
        core = self._core(self._StalledLib(), default_timeout=0.1)
        with pytest.raises(HorovodTimeoutError):
            core.wait(7)  # no explicit timeout: the env default bounds it

    def test_completed_wait_unaffected_by_timeout(self):
        core = self._core(self._DoneLib())
        core.wait(7, timeout=5.0)  # polls true immediately; no error


# --------------------------------------------------------------- supervisor


def _result(codes, trigger=None):
    return JobResult(exit_codes=codes, trigger=trigger)


class TestSupervisor:
    def _fake_launch(self, outcomes, seen_envs):
        outcomes = list(outcomes)

        def launch(cmd, np, hosts=None, env=None, jax_distributed=False):
            seen_envs.append(dict(env or {}))
            return outcomes.pop(0)

        return launch

    def test_crash_relaunches_then_clean(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1,
            _launch=self._fake_launch([
                _result({0: -9, 1: -15}, WorkerExit(0, -9)),
                _result({0: 0, 1: 0}),
            ], envs))
        assert rc == 0 and len(envs) == 2
        assert envs[0]["HOROVOD_ELASTIC_RESTART"] == "0"
        assert envs[1]["HOROVOD_ELASTIC_RESTART"] == "1"
        assert all(e["HOROVOD_ELASTIC"] == "1" for e in envs)

    def test_crash_budget_exhausted_returns_code(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1,
            _launch=self._fake_launch([
                _result({0: -9}, WorkerExit(0, -9)),
                _result({0: 1}, WorkerExit(0, 1)),
            ], envs))
        assert rc == 1 and len(envs) == 2

    def test_usage_error_never_relaunches(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=5,
            _launch=self._fake_launch(
                [_result({0: 2}, WorkerExit(0, 2))], envs))
        assert rc == EXIT_USAGE and len(envs) == 1

    def test_preemptions_relaunch_for_free(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=0,
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: -15}, WorkerExit(0, -15)),
                _result({0: 0}),
            ], envs))
        assert rc == 0 and len(envs) == 3

    def test_count_preemptions_restores_strict_budget(self):
        envs = []
        rc = elastic.supervise(
            ["prog"], np=2, max_restarts=1, count_preemptions=True,
            _launch=self._fake_launch([
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
                _result({0: EXIT_PREEMPTED}, WorkerExit(0, EXIT_PREEMPTED)),
            ], envs))
        assert rc == EXIT_PREEMPTED and len(envs) == 2


# ------------------------------------------------------------- elastic loop


class TestRunElastic:
    def test_resume_is_bit_exact_plain(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, met_full, r0 = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3)
        assert r0 == 0
        # Interrupted run: 6 steps, then a fresh invocation to 12 —
        # exactly what a relaunch does.
        m = CheckpointManager(str(tmp_path / "ckpt"), backend="numpy")
        _, met_a, _ = elastic.run_elastic(
            step_fn, init, batch_for, 6, manager=m, snapshot_every=3)
        s_b, met_b, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m, snapshot_every=3)
        assert resumed == 6
        assert float(np.asarray(s_b["w"])) == float(np.asarray(s_full["w"]))
        traj_full = {s: float(m_["loss"]) for s, m_ in met_full}
        traj_ab = {s: float(m_["loss"]) for s, m_ in met_a + met_b}
        assert traj_ab == traj_full  # identical loss trajectory

    def test_resume_is_bit_exact_windowed(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, met_full, _ = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3, steps_per_dispatch=3)
        m = CheckpointManager(str(tmp_path / "ckpt"), backend="numpy")
        elastic.run_elastic(step_fn, init, batch_for, 6, manager=m,
                            snapshot_every=3, steps_per_dispatch=3)
        s_b, met_b, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m,
            snapshot_every=3, steps_per_dispatch=3)
        assert resumed == 6
        assert float(np.asarray(s_b["w"])) == float(np.asarray(s_full["w"]))
        # Window metric means replay identically too.
        full = {s: float(m_["loss"]) for s, m_ in met_full}
        replay = {s: float(m_["loss"]) for s, m_ in met_b}
        for s, v in replay.items():
            assert full[s] == v

    def test_finished_run_reinvocation_is_noop_resume(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m = CheckpointManager(str(tmp_path), backend="numpy")
        s1, _, _ = elastic.run_elastic(step_fn, init, batch_for, 6,
                                       manager=m, snapshot_every=3)
        s2, met2, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 6, manager=m, snapshot_every=3)
        assert resumed == 6 and met2 == []
        assert float(np.asarray(s2["w"])) == float(np.asarray(s1["w"]))

    def test_preemption_at_boundary_saves_and_exits_75(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        m = CheckpointManager(str(tmp_path), backend="numpy")
        handler = elastic.PreemptionHandler(install=False)
        inj = elastic.FaultInjector(
            elastic.parse_fault_plan("preempt:rank=0,step=4"),
            rank=0, attempt=0)
        with pytest.raises(SystemExit) as ei:
            elastic.run_elastic(step_fn, init, batch_for, 12, manager=m,
                                snapshot_every=2, injector=inj,
                                preemption=handler)
        assert ei.value.code == EXIT_PREEMPTED
        manifest = elastic.latest_manifest(str(tmp_path))
        assert manifest.step == 4  # drained + snapshotted at the boundary
        # And the relaunch resumes exactly there, to the same final state.
        s_resumed, _, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m, snapshot_every=2)
        m_full = CheckpointManager(str(tmp_path / "full"), backend="numpy")
        s_full, _, _ = elastic.run_elastic(step_fn, init, batch_for, 12,
                                           manager=m_full, snapshot_every=2)
        assert resumed == 4
        assert float(np.asarray(s_resumed["w"])) == \
            float(np.asarray(s_full["w"]))

    def test_sharded_batch_source_cursor(self):
        root = np.random.RandomState(0)
        src = elastic.ShardedBatchSource(
            {"x": root.normal(size=(40, 2)).astype(np.float32)},
            batch_size=4, rank=1, size=2, seed=3)
        assert src.steps_per_epoch == 5
        cur = src.cursor(7)
        assert cur == {"epoch": 1, "offset": 8, "rank": 1, "size": 2}
        # Deterministic in the step — the whole resume argument.
        np.testing.assert_array_equal(src.batch_at(7)["x"],
                                      src.batch_at(7)["x"])
        # Disjoint from the other rank's shard at the same step.
        other = elastic.ShardedBatchSource(
            {"x": src.arrays["x"]}, batch_size=4, rank=0, size=2, seed=3)
        assert not np.array_equal(src.batch_at(0)["x"],
                                  other.batch_at(0)["x"])

    def test_prebuilt_snapshotter_resumes_too(self, tmp_path):
        """The composable path — run_elastic(snapshotter=Snapshotter(
        manager=...)) with no manager kwarg — must resume and final-
        flush exactly like the manager kwarg path (review finding: the
        gates used to check the kwarg only)."""
        step_fn, batch_for, init = _toy_step()
        mngr = CheckpointManager(str(tmp_path), backend="numpy")
        elastic.run_elastic(
            step_fn, init, batch_for, 6,
            snapshotter=elastic.Snapshotter(mngr, every=3))
        assert elastic.latest_manifest(str(tmp_path)).step == 6
        s2, _, resumed = elastic.run_elastic(
            step_fn, init, batch_for, 12,
            snapshotter=elastic.Snapshotter(mngr, every=3))
        assert resumed == 6
        m_full = CheckpointManager(str(tmp_path / "full"),
                                   backend="numpy")
        s_full, _, _ = elastic.run_elastic(
            step_fn, init, batch_for, 12, manager=m_full,
            snapshot_every=3)
        assert float(np.asarray(s2["w"])) == float(np.asarray(s_full["w"]))

    def test_flush_with_state_requires_step(self):
        snap = elastic.Snapshotter(every=1)
        with pytest.raises(ValueError, match="step"):
            snap.flush(state={"w": jnp.float32(1.0)})

    def test_misaligned_cadence_rejected(self, tmp_path):
        step_fn, batch_for, init = _toy_step()
        with pytest.raises(ValueError, match="window"):
            elastic.run_elastic(
                step_fn, init, batch_for, 12,
                manager=CheckpointManager(str(tmp_path), backend="numpy"),
                snapshot_every=4, steps_per_dispatch=3)


# ------------------------------------------------------------ flax binding


class TestElasticSnapshotCallback:
    def _loop_pieces(self):
        import horovod_tpu.flax as hvd_flax

        def step_fn(state, batch):
            return ({"w": state["w"] - 0.1 * batch["x"],
                     "step": state["step"] + 1},
                    {"loss": jnp.sum(state["w"])})

        def data_fn(epoch):
            for i in range(4):
                yield {"x": jnp.float32(i + 1)}

        init = {"w": jnp.float32(1.0), "step": jnp.int32(0)}
        return hvd_flax, step_fn, data_fn, init

    def test_cadence_snapshots_and_final_flush(self, tmp_path):
        hvd_flax, step_fn, data_fn, init = self._loop_pieces()
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=4, spill_every=1)
            loop = hvd_flax.TrainLoop(
                init, step_fn, data_fn,
                callbacks=[hvd_flax.ElasticSnapshotCallback(snap)])
            loop.fit(epochs=2)  # 8 steps: cadence spill at 4, flush at 8
            assert mngr.all_steps() == [4, 8]
            restored, manifest = snap.restore(init)
            assert manifest.step == 8
            np.testing.assert_array_equal(np.asarray(restored["w"]),
                                          np.asarray(loop.state["w"]))

    def test_preemption_mid_fit_saves_and_exits(self, tmp_path):
        hvd_flax, step_fn, data_fn, init = self._loop_pieces()
        with CheckpointManager(str(tmp_path), backend="numpy") as mngr:
            snap = elastic.Snapshotter(mngr, every=100)
            handler = elastic.PreemptionHandler(install=False)

            class TriggerAtStep3(hvd_flax.Callback):
                def on_batch_end(self, batch, logs=None):
                    if int(self.loop.state["step"]) == 3:
                        handler.trigger()

            loop = hvd_flax.TrainLoop(
                init, step_fn, data_fn,
                callbacks=[TriggerAtStep3(),
                           hvd_flax.ElasticSnapshotCallback(
                               snap, preemption=handler)])
            with pytest.raises(SystemExit) as ei:
                loop.fit(epochs=2)
            assert ei.value.code == EXIT_PREEMPTED
            assert elastic.latest_manifest(str(tmp_path)).step == 3


# ------------------------------------------------------------------- e2e


def _last_wins(path: Path) -> dict:
    out = {}
    for line in path.read_text().splitlines():
        step, value = line.split()
        out[int(step)] = value
    return out


def _run_elastic_job(tmp_path, tag, steps, every, k, fault=None,
                     expect_rc=0):
    out = tmp_path / f"{tag}-out"
    ckpt = tmp_path / f"{tag}-ckpt"
    cmd = [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
           "--elastic", "--max-restarts", "1"]
    if fault:
        cmd += ["--fault-plan", fault]
    cmd += [sys.executable, str(REPO / "tests" / "elastic_worker.py"),
            str(out), str(ckpt), str(steps), str(every), str(k)]
    proc = subprocess.run(cmd, env=_clean_env(), cwd=str(REPO),
                          timeout=600, capture_output=True, text=True)
    assert proc.returncode == expect_rc, (proc.stdout[-2000:],
                                          proc.stderr[-2000:])
    return out, proc


class TestEndToEnd:
    """Acceptance: `hvdrun --elastic --max-restarts 1` with a fault plan
    killing rank 1 mid-run resumes from the snapshot and finishes with a
    bit-exact final state and loss trajectory vs. the fault-free run."""

    @pytest.mark.parametrize("k", [1, 3])
    def test_kill_rank1_resumes_bit_exact(self, tmp_path, k):
        steps, every = 18, 3
        clean_out, _ = _run_elastic_job(tmp_path, f"clean{k}", steps,
                                        every, k)
        fault_out, proc = _run_elastic_job(
            tmp_path, f"fault{k}", steps, every, k,
            fault="kill:rank=1,step=7")
        # The supervisor actually classified the SIGKILL and relaunched.
        assert "crashed" in proc.stderr
        assert "relaunching all ranks" in proc.stderr
        for rank in (0, 1):
            clean_final = (clean_out / f"rank{rank}.final").read_text()
            fault_final = (fault_out / f"rank{rank}.final").read_text()
            # Same weights bit-for-bit (the digest covers every leaf).
            assert clean_final.split()[0] == fault_final.split()[0]
            # The interrupted+resumed trajectory equals the fault-free
            # one at every step it recorded (repr equality = bit-exact).
            clean_traj = _last_wins(clean_out / f"rank{rank}.traj")
            fault_traj = _last_wins(fault_out / f"rank{rank}.traj")
            assert fault_traj == clean_traj
        # The killed rank really did resume from a mid-run snapshot.
        assert "resumed=0" not in (fault_out / "rank1.final").read_text()

    def test_malformed_fault_plan_is_usage_error(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run", "-np", "1",
             "--elastic", "--fault-plan", "explode:rank=0",
             sys.executable, "-c", "pass"],
            env=_clean_env(), cwd=str(REPO), timeout=120,
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "fault plan" in proc.stderr
