"""TRUE multi-process SPMD: two OS processes, each owning 2 virtual CPU
chips, joined into one 4-chip mesh by the launcher's --jax mode. This is
the closest single-machine analogue of the reference's ``mpirun -np 2``
integration tests (SURVEY §4 mechanism 1) for the flagship lane: real
jax.distributed bootstrap, real cross-process collectives (Gloo), real
host-local<->global dispatch conversion — nothing mocked.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "spmd_multiproc_worker.py"


def _launch_and_check(extra_env=None, np_=2, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", str(np_), "--jax",
         sys.executable, str(WORKER)],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=timeout)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-3000:]}\n"
        f"stderr:{proc.stderr[-3000:]}")
    results = re.findall(r"RESULT rank=(\d) digest=(\w+) loss=([\d.]+)",
                         proc.stdout)
    assert len(results) == np_, proc.stdout
    by_rank = {int(r): (d, float(l)) for r, d, l in results}
    assert set(by_rank) == set(range(np_))
    # Same averaged gradients + same broadcast start => identical params.
    for r in range(1, np_):
        assert by_rank[0][0] == by_rank[r][0], by_rank
        assert by_rank[0][1] == by_rank[r][1]


def test_two_process_global_mesh_end_to_end():
    _launch_and_check()


def test_two_process_hierarchical_ladder():
    """The same end-to-end story with HOROVOD_HIERARCHICAL_* set: the
    4-chip axis spans 2 processes x 2 chips, so the auto inner size is 2
    and every fused gradient reduction runs the explicit two-level ladder
    (reduce-scatter within the process's chips, cross-reduce over the
    process boundary, all-gather back — horovod_tpu/jax/fusion.py ->
    parallel/mesh.py). Every worker assert (closed-form collectives,
    convergence, ZeRO sharding, ring attention, cross-process digest
    equality) must still hold."""
    _launch_and_check({"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                       "HOROVOD_HIERARCHICAL_ALLGATHER": "1"})


def test_four_process_global_mesh_end_to_end():
    """np=4 (8 chips): alltoall has 4-way splits, ring attention's K/V
    blocks traverse 4 process boundaries, ZeRO shards over 8 chips —
    sizes where a transposed index or an off-by-one rank map that np=2
    cannot distinguish from its inverse actually shows (reference
    size-parametric mpirun -np N strategy, test/common.py:25-58)."""
    _launch_and_check(np_=4, timeout=900)


def test_four_process_hierarchical_ladder():
    """The two-level ladder's first non-degenerate topology: 4 local
    groups of 2, so the CROSS ring has 4 members (np=2's cross ring of 2
    is just a pairwise exchange) — ordering bugs in the cross-reduce
    only exist from 3 members up."""
    _launch_and_check({"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                       "HOROVOD_HIERARCHICAL_ALLGATHER": "1"},
                      np_=4, timeout=900)


def test_eight_process_asymmetric_ladder_and_ulysses():
    """np=8 x 1 chip (VERDICT r4 #7): the 8-chip global mesh factored
    2 (cross) x 4 (local) by HIERARCHICAL_INNER_SIZE=4 — the ladder's
    first UNEQUAL local/cross split (auto mode always chose local ==
    chips-per-process, so 2x4 was unreachable before this knob), with
    inner groups genuinely spanning 4 processes; plus the worker's
    ulysses section issuing true 8-way alltoalls across all 8 process
    boundaries (reference size-parametric mpirun -np N strategy,
    test/common.py:25-58)."""
    _launch_and_check({"HVD_TEST_LOCAL_CHIPS": "1",
                       "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                       "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
                       "HOROVOD_HIERARCHICAL_INNER_SIZE": "4"},
                      np_=8, timeout=1200)
