"""TRUE multi-process SPMD: two OS processes, each owning 2 virtual CPU
chips, joined into one 4-chip mesh by the launcher's --jax mode. This is
the closest single-machine analogue of the reference's ``mpirun -np 2``
integration tests (SURVEY §4 mechanism 1) for the flagship lane: real
jax.distributed bootstrap, real cross-process collectives (Gloo), real
host-local<->global dispatch conversion — nothing mocked.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "spmd_multiproc_worker.py"


def test_two_process_global_mesh_end_to_end():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--jax",
         sys.executable, str(WORKER)],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-3000:]}\n"
        f"stderr:{proc.stderr[-3000:]}")
    results = re.findall(r"RESULT rank=(\d) digest=(\w+) loss=([\d.]+)",
                         proc.stdout)
    assert len(results) == 2, proc.stdout
    by_rank = {int(r): (d, float(l)) for r, d, l in results}
    assert set(by_rank) == {0, 1}
    # Same averaged gradients + same broadcast start => identical params.
    assert by_rank[0][0] == by_rank[1][0], by_rank
    assert by_rank[0][1] == by_rank[1][1]
