"""Tests for the native C++ core (csrc/): coordinator, ring collectives,
fusion, negotiation errors, timeline, autotuner.

Strategy parity with the reference (SURVEY §4): size-parametric correctness
with closed-form assertions, fusion by volume, negotiation-mismatch error
tests, timeline artifact assertions. The reference launched via
``mpirun -np N``; here N subprocesses rendezvous over the native TCP
transport.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "native_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(size: int, scenario: str, extra_env=None, timeout=120):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)  # native core tests don't need jax
    procs = []
    for rank in range(size):
        rank_env = dict(env)
        if extra_env:
            rank_env.update(extra_env.get(rank, {}))
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER), str(rank), str(size), str(port),
             scenario],
            env=rank_env, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    failures = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        if p.returncode != 0:
            failures.append(
                f"rank {rank} rc={p.returncode}\n{err.decode()[-2000:]}")
    assert not failures, "\n".join(failures)


@pytest.fixture()
def core():
    from horovod_tpu.native import NativeCore

    c = NativeCore()
    c.init()
    yield c
    c.shutdown()


class TestSingleProcess:
    def test_build_and_init(self, core):
        assert core.initialized
        assert core.rank() == 0
        assert core.size() == 1
        assert core.local_rank() == 0
        assert core.local_size() == 1

    def test_allreduce_identity(self, core):
        a = np.arange(17, dtype=np.float32)
        h = core.allreduce_async_("t", a)
        core.wait(h)
        core.release(h)
        assert np.allclose(a, np.arange(17))

    def test_allgather_copy(self, core):
        g = np.random.randn(4, 3).astype(np.float64)
        h = core.allgather_async("g", g)
        core.wait(h)
        out = core.take_result(h, np.float64, (3,))
        assert np.allclose(out, g)

    def test_broadcast_identity(self, core):
        b = np.full(5, 7, dtype=np.int32)
        h = core.broadcast_async_("b", b, 0)
        core.wait(h)
        core.release(h)
        assert (b == 7).all()

    def test_poll_eventually_true(self, core):
        a = np.ones(4, dtype=np.float32)
        h = core.allreduce_async_("p", a)
        core.wait(h)
        assert core.poll(h)
        core.release(h)

    def test_duplicate_name_rejected(self, core):
        from horovod_tpu.native import NativeError

        import time

        core.set_cycle_time_ms(200.0)
        # Let the in-flight short sleep drain so the background thread is
        # parked in a 200 ms sleep and can't race between the two enqueues.
        time.sleep(0.05)
        h1 = core.allreduce_async_("dup", np.zeros(4, np.float32))
        with pytest.raises(NativeError, match="Duplicate"):
            core.allreduce_async_("dup", np.zeros(4, np.float32))
        core.wait(h1)
        core.release(h1)
        core.set_cycle_time_ms(1.0)

    def test_knobs_roundtrip(self, core):
        core.set_fusion_threshold(1 << 20)
        assert core.fusion_threshold() == 1 << 20
        core.set_cycle_time_ms(2.5)
        assert abs(core.cycle_time_ms() - 2.5) < 1e-9

    def test_allgather_scalar_rejected(self, core):
        """0-d tensors can't concatenate along a first dim; must error,
        not crash (regression: size==1 path skipped validation)."""
        from horovod_tpu.native import NativeError

        h = core.allgather_async("scalar", np.array(3.0, dtype=np.float32))
        with pytest.raises(NativeError, match="at least one dimension"):
            core.wait(h)

    def test_take_result_shape_mismatch_rejected(self, core):
        from horovod_tpu.native import NativeError

        g = np.ones((3, 3), dtype=np.float32)
        h = core.allgather_async("mismatch", g)
        core.wait(h)
        with pytest.raises(NativeError, match="not divisible"):
            core.take_result(h, np.float64, (3,))

    def test_timeline_name_escaping(self, core, tmp_path):
        path = tmp_path / "tl.json"
        core.timeline_start(str(path))
        a = np.ones(4, dtype=np.float32)
        h = core.allreduce_async_('weird"name\\x', a)
        core.wait(h)
        core.release(h)
        core.timeline_end()
        events = json.loads(path.read_text().rstrip().rstrip(",") + "]")
        assert any(e.get("args", {}).get("name") == 'weird"name\\x'
                   for e in events if e.get("name") == "process_name")

    def test_dtypes_roundtrip(self, core):
        for dt in (np.uint8, np.int8, np.int16, np.int32, np.int64,
                   np.float16, np.float32, np.float64):
            a = np.ones(9, dtype=dt)
            h = core.allreduce_async_(f"dt.{np.dtype(dt).name}", a)
            core.wait(h)
            core.release(h)
            assert (a == 1).all()


class TestMultiProcess:
    @pytest.mark.parametrize("size", [2, 4])
    def test_collectives(self, size):
        _spawn(size, "collectives")

    def test_negotiation_errors(self):
        _spawn(2, "errors")


class TestSubCommunicator:
    """init(comm=[subset]) on the native TCP lane (reference
    hvd.init(comm=...), common/__init__.py:58-84): the world rendezvous
    resolves each sub-world's coordinator through the control star, then
    members run on their own star/ring."""

    def test_three_ranks_pair_plus_sitout(self):
        """World ranks {0,2} run collectives while rank 1 sits out on its
        singleton — the round-3 verdict's acceptance scenario."""
        _spawn(3, "subcomm")

    def test_four_ranks_two_concurrent_subworlds(self):
        """Two disjoint pairs {0,2} and {1,3} form and run collectives
        CONCURRENTLY off one launcher rendezvous — no cross-world mixing
        (the closed forms sum member world-ranks only)."""
        _spawn(4, "subcomm")

    def test_hierarchical_knob_degrades_to_flat_in_subworlds(self):
        """A sub-world regroups local_size to its member count (one
        host here), so the two-level ladder cannot tile (inner == size)
        and must degrade to the flat ring per sub-world — collectives
        stay correct rather than deadlocking on a mixed dial."""
        env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
               "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
               "HVD_TEST_WANT_HIER": "0"}
        _spawn(4, "subcomm", extra_env={r: dict(env) for r in range(4)})

    def test_inconsistent_split_fails_on_every_rank(self):
        """Rank 0 claims {0,1} while rank 1 claims its singleton (and
        rank 2 its own): the global validation fails every rank together
        — MPI's collective communicator-creation failure semantics.
        (Three ranks so rank 0's claim is a PROPER subset: a full-world
        comm takes the no-rendezvous fast path by design.)"""
        _spawn(3, "subcomm_mismatch")


class TestStallDetection:
    def test_stall_warning_emitted_and_job_recovers(self):
        """A rank that holds back one collective must provably produce the
        rank-0 stall warning naming the missing rank (reference
        CheckForStalledTensors, operations.cc:1625-1672), and the job must
        still complete once the straggler arrives."""
        port = _free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("JAX_PLATFORMS", None)
        env["HOROVOD_STALL_WARNING_TIME"] = "0.5"
        procs = []
        for rank in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(WORKER), str(rank), "2", str(port),
                 "stall"],
                env=env, cwd=str(REPO),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        outs = []
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            outs.append(err.decode())
            assert p.returncode == 0, f"rank {rank}: {err.decode()[-2000:]}"
        assert "waiting for remainder of ranks" in outs[0], outs[0][-2000:]
        assert "missing ranks: 1" in outs[0], outs[0][-2000:]


class TestFusionKnob:
    def test_fusion_disabled_still_correct(self):
        """HOROVOD_FUSION_THRESHOLD=0 disables fusion (one collective per
        tensor, reference operations.cc semantics); the volume scenario's
        64 concurrent small tensors must still reduce to closed form."""
        env = {"HOROVOD_FUSION_THRESHOLD": "0"}
        _spawn(2, "collectives", extra_env={0: dict(env), 1: dict(env)})


class TestHierarchical:
    """Two-level (local ring + cross ring) collectives on the native lane
    (reference hierarchical allreduce operations.cc:1284-1436, hierarchical
    allgather :929-1032; knobs operations.h:65-66)."""

    def test_hierarchical_allreduce_allgather_4ranks_2groups(self):
        """4 ranks tiled as 2 groups of 2: the hierarchical path must be
        active on every rank and every collective must match the flat
        closed forms (worker scenario asserts both)."""
        env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
               "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
               "HOROVOD_HIERARCHICAL_INNER_SIZE": "2"}
        _spawn(4, "hier", extra_env={r: dict(env) for r in range(4)})

    def test_hierarchical_knob_mismatch_unifies(self):
        """A partially-propagated env (knobs AND inner size on rank 0
        only) used to hang at the bootstrap barrier; the coordinator now
        exchanges votes + inner size through the control star, every
        rank adopts the union and the root's resolved group shape (mixed
        per-rank algorithms or group shapes would deadlock
        mid-collective), and the job completes with the hierarchical
        path active everywhere."""
        on = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
              "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
              "HOROVOD_HIERARCHICAL_INNER_SIZE": "2",
              "HVD_TEST_WANT_HIER": "3"}
        # Ranks 1-3 get NEITHER the knobs NOR the inner size; the
        # WANT override pins what the unified decision must be.
        off = {"HVD_TEST_WANT_HIER": "3"}
        _spawn(4, "hier",
               extra_env={0: dict(on), 1: dict(off), 2: dict(off),
                          3: dict(off)})

    def test_hierarchical_authenticated(self):
        """The local/cross hierarchy links run the same HMAC handshake as
        the flat ring (csrc/auth.cc kAuthPurposeHier)."""
        secret = os.urandom(16).hex()
        env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
               "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
               "HOROVOD_HIERARCHICAL_INNER_SIZE": "2",
               "HOROVOD_SECRET": secret}
        _spawn(4, "hier", extra_env={r: dict(env) for r in range(4)})

    def test_group_size_defaults_to_local_size(self):
        """Without HOROVOD_HIERARCHICAL_INNER_SIZE the group size is the
        launcher-provided local_size — the reference's grouping by host
        (local_comm split, operations.cc:1760-1797). Simulate 2 hosts x 2
        ranks via HOROVOD_LOCAL_RANK/LOCAL_SIZE."""
        def env(rank):
            return {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
                    "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
                    "HOROVOD_LOCAL_SIZE": "2",
                    "HOROVOD_LOCAL_RANK": str(rank % 2)}
        _spawn(4, "hier", extra_env={r: env(r) for r in range(4)})

    def test_untileable_topology_degrades_to_flat(self):
        """size=3 with inner=2 can't tile into equal groups: the knob must
        degrade to the flat ring (hierarchical_active()==0) with results
        still correct — the analogue of the reference's heterogeneous
        degrade (operations.cc:1303-1315)."""
        env = {"HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
               "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
               "HOROVOD_HIERARCHICAL_INNER_SIZE": "2"}
        _spawn(3, "hier", extra_env={r: dict(env) for r in range(3)})


class TestTransportAuth:
    """The TCP transport authenticates every connection with an
    HMAC-SHA256 challenge-response keyed by HOROVOD_SECRET (csrc/auth.cc),
    mirroring the launcher wire's HMAC (run/network.py)."""

    def test_matching_secret_works(self):
        secret = os.urandom(16).hex()
        _spawn(2, "collectives",
               extra_env={0: {"HOROVOD_SECRET": secret},
                          1: {"HOROVOD_SECRET": secret}})

    def test_mismatched_secret_rejected(self):
        """A peer without the job secret must not be able to claim a rank
        slot (round-1 advisory: unauthenticated rank hijack -> RCE via
        pickled broadcast)."""
        port = _free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("JAX_PLATFORMS", None)
        secrets = [os.urandom(16).hex(), os.urandom(16).hex()]
        procs = []
        for rank in range(2):
            rank_env = dict(env)
            rank_env["HOROVOD_SECRET"] = secrets[rank]
            # The rejected rank retries until the bootstrap timeout; a
            # short one keeps this failure-path test fast.
            rank_env["HVD_TEST_INIT_TIMEOUT_MS"] = "6000"
            procs.append(subprocess.Popen(
                [sys.executable, str(WORKER), str(rank), "2", str(port),
                 "collectives"],
                env=rank_env, cwd=str(REPO),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        errs = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            errs.append(err.decode())
        assert all(p.returncode != 0 for p in procs), (
            "init succeeded despite mismatched HOROVOD_SECRET\n"
            + "\n".join(errs))
        assert any("authentication failed" in e for e in errs), errs


class TestTimeline:
    def test_chrome_trace_written(self, tmp_path):
        """Timeline artifact assertions, parity with reference
        test/test_timeline.py:42-58."""
        from horovod_tpu.native import NativeCore

        path = tmp_path / "timeline.json"
        core = NativeCore()
        core.init()
        core.timeline_start(str(path), mark_cycles=True)
        a = np.ones(8, dtype=np.float32)
        h = core.allreduce_async_("tl_tensor", a)
        core.wait(h)
        core.release(h)
        core.timeline_end()
        core.shutdown()

        text = path.read_text()
        # Unclosed JSON array format: make it parseable.
        events = json.loads(text.rstrip().rstrip(",") + "]")
        names = [e.get("name") for e in events]
        assert "process_name" in names
        assert any(e.get("args", {}).get("name") == "tl_tensor"
                   for e in events if e.get("name") == "process_name")
        assert "ALLREDUCE" in names
        assert "RING_ALLREDUCE" in names
        phases = {e.get("ph") for e in events}
        assert {"B", "E", "M"} <= phases


class TestAutotune:
    def test_autotune_params_sync_across_ranks(self):
        """Rank-0's tuned {cycle time, fusion threshold} reach every rank
        (reference SyncParams semantics, parameter_manager.h:95-96,232)."""
        _spawn(2, "autotune_sync", timeout=150)

    def _drive_pm(self, hier_available, score_fn, max_feeds=64):
        """Drive the native ParameterManager deterministically through
        the test shim: score each suggested candidate with ``score_fn``
        until convergence; returns the winning (threshold, hier)."""
        import ctypes as c

        from horovod_tpu.native import load_library

        lib = load_library()
        pm = lib.hvdtpu_pm_create(1 if hier_available else 0)
        try:
            cyc = c.c_double(5.0)
            thr = c.c_longlong(64 << 20)
            hier = c.c_int(0)
            for _ in range(max_feeds):
                score = score_fn(thr.value, hier.value)
                done = lib.hvdtpu_pm_feed(
                    pm, float(score), c.byref(cyc), c.byref(thr),
                    c.byref(hier))
                if done:
                    return thr.value, hier.value
            raise AssertionError("ParameterManager never converged")
        finally:
            lib.hvdtpu_pm_destroy(pm)

    def test_tuner_flips_hierarchy_by_throughput(self):
        """Categorical autotuning (reference parameter_manager.h:149-205
        swept hierarchical allreduce/allgather alongside the numeric
        pair): when the two-level ladder's windows score 2x the flat
        ring's bytes/sec, the converged winner must carry both
        hierarchical bits — and with the scores reversed, neither."""
        _, hier = self._drive_pm(
            True, lambda t, h: 2e9 if h == 3 else 1e9)
        assert hier == 3, hier

        _, hier = self._drive_pm(
            True, lambda t, h: 0.5e9 if h else 1e9)
        assert hier == 0, hier

    def test_tuner_without_hierarchy_stays_flat(self):
        """Sub-rings not dialed: the categorical space collapses to the
        flat combo regardless of scores."""
        _, hier = self._drive_pm(False, lambda t, h: 1e9 + t)
        assert hier == 0

    def test_gp_hyperparameter_fit_adapts(self):
        """The GP now fits {length scale, signal variance} by maximizing
        the log marginal likelihood (reference gaussian_process.h:32-60);
        the native self-test checks the kernel adapts to data roughness
        and still interpolates."""
        from horovod_tpu.native import load_library

        lib = load_library()
        assert lib.hvdtpu_gp_selftest() == 1

    def test_autotune_log_and_convergence(self, tmp_path):
        from horovod_tpu.native import NativeCore

        log = tmp_path / "autotune.tsv"
        core = NativeCore()
        core.init()
        core.set_cycle_time_ms(0.2)
        core.enable_autotune(str(log))
        # Drive enough scored windows (10 cycles each) to pass warmup and
        # produce Bayesian samples.
        for step in range(160):
            a = np.ones(1024, dtype=np.float32)
            h = core.allreduce_async_(f"at.{step}", a)
            core.wait(h)
            core.release(h)
        core.shutdown()
        lines = log.read_text().strip().splitlines()
        assert len(lines) >= 4
        kinds = {line.split("\t")[1] for line in lines}
        assert "warmup" in kinds
        assert "sample" in kinds
        # Scores are positive bytes/sec.
        assert all(float(line.split("\t")[4]) > 0 for line in lines)
