"""Hierarchical bucket collectives + low-bit DCN wire compression
(horovod_tpu/jax/fusion.py, HOROVOD_HIERARCHICAL): the ladder changes
WIRE SHAPE — intra-slice reduce-scatter, inter-slice exchange of the
1/inner shard (optionally int8/fp8-quantized with error feedback),
intra-slice all-gather — and, for ``Compression.none``, NEVER numerics:
pinned bit-exactly against the flat psum over the 8-chip virtual mesh
with integer-valued tensors (every summation order exact), at both DCN
exchange shapes (inner 4 -> 2 slices, all-gather exchange; inner 2 ->
4 slices, two-stage all-to-all). The quantized wire is pinned three
ways: exactly on quantization-grid data (the Average no-double-scaling
contract from the fusion.py dtype-ladder table), within tolerance on
random data, and by an error-feedback convergence run on a small LM
(quantized-DP loss trajectory near fp32 DP and strictly better than
feedback-free quantization).
"""

import contextlib

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvd
from horovod_tpu.common import state as _state
from horovod_tpu.common.exceptions import InvalidArgumentError
from horovod_tpu.jax.fusion import (
    ef_residual_specs,
    fused_reduce,
    hier_bucket_layout,
    hier_wire_summary,
    plan_buckets,
    resolve_hierarchical,
)

_SHAPES = [(33,), (7, 5), (101,), (4, 4, 4), (257,)]
_THRESHOLD = 400  # multi-bucket plan incl. an oversize singleton


@contextlib.contextmanager
def _inner_size(inner):
    st = _state.global_state()
    saved = st.config.hierarchical_inner_size
    st.config.hierarchical_inner_size = inner
    try:
        yield
    finally:
        st.config.hierarchical_inner_size = saved


@contextlib.contextmanager
def _config_mode(mode):
    """Pin the HOROVOD_HIERARCHICAL tri-state default for assertions on
    mode=None resolution (another test file may have left a non-default
    value behind — e.g. the autotuner legitimately applies its winner
    to the live config)."""
    st = _state.global_state()
    saved = st.config.hierarchical
    st.config.hierarchical = mode
    try:
        yield
    finally:
        st.config.hierarchical = saved


def _bases(seed=0, lo=-8, hi=8):
    rng = np.random.RandomState(seed)
    return [np.asarray(rng.randint(lo, hi, size=s), np.float32)
            for s in _SHAPES]


def _run(bases, *, hierarchical, inner, overlap="off", average=True,
         compression=None, threshold=_THRESHOLD):
    comp = compression or hvd.Compression.none

    def fn():
        ts = [b * (hvd.rank() + 1).astype(b.dtype) for b in bases]
        return tuple(fused_reduce(ts, average=average, compression=comp,
                                  fusion_threshold=threshold,
                                  overlap=overlap,
                                  hierarchical=hierarchical))

    with _inner_size(inner):
        return [np.asarray(o) for o in hvd.spmd_run(fn)]


# ------------------------------------------------- flat-vs-hier exactness


@pytest.mark.parametrize("inner", [4, 2])
@pytest.mark.parametrize("overlap", ["off", "on"])
@pytest.mark.parametrize("average", [False, True])
def test_hier_matches_flat_bitexact(hvd, inner, overlap, average):
    """Compression.none: the hierarchical ladder is a wire-shape change
    only — bit-identical to the flat psum at every inner size and
    overlap mode (integer-valued tensors make every summation order
    exact, so one differing bit is a semantic change)."""
    bases = _bases()
    ref = _run(bases, hierarchical="off", inner=0, average=average)
    got = _run(bases, hierarchical="on", inner=inner, overlap=overlap,
               average=average)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_hier_cast_compression_bitexact(hvd):
    """fp16 wire rides the ladder unchanged: the whole bucket is fp16 on
    every leg and the 1/n divide stays at the decompressed tail (dtype
    ladder table, fusion.py) — hier on/off share one reduction +
    division sequence exactly."""
    bases = _bases(seed=1)
    ref = _run(bases, hierarchical="off", inner=0,
               compression=hvd.Compression.fp16)
    got = _run(bases, hierarchical="on", inner=4,
               compression=hvd.Compression.fp16)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_hier_min_falls_back_to_flat(hvd):
    """Min/Max have no scatter primitive: hierarchical mode must still
    produce the identical flat-path result."""
    bases = _bases(seed=2, lo=0, hi=9)

    def fn(hierarchical, inner):
        def inner_fn():
            ts = [b * (hvd.rank() + 1).astype(b.dtype) for b in bases]
            return tuple(fused_reduce(ts, op=hvd.Min,
                                      fusion_threshold=_THRESHOLD,
                                      hierarchical=hierarchical))
        with _inner_size(inner):
            return [np.asarray(o) for o in hvd.spmd_run(inner_fn)]

    for r, g in zip(fn("off", 0), fn("on", 4)):
        np.testing.assert_array_equal(r, g)


# ------------------------------------------------------- knob resolution


def test_resolve_hierarchical_semantics(hvd):
    st = _state.global_state()
    assert resolve_hierarchical("off", 8) == 0
    with _inner_size(4):
        assert resolve_hierarchical("on", 8) == 4
        assert resolve_hierarchical(True, 8) == 4
        assert resolve_hierarchical(False, 8) == 0
        # inner must strictly divide (1 < inner < axis): degrade to flat.
        assert resolve_hierarchical("on", 4) == 0
    with _inner_size(3):
        assert resolve_hierarchical("on", 8) == 0
    # auto keys off a DCN boundary; the CPU harness is one process ->
    # flat, even with an explicit inner size.
    from horovod_tpu.parallel.mesh import dcn_present

    assert not dcn_present(st.devices)
    assert resolve_hierarchical("auto", 8) == 0
    with _inner_size(4):
        assert resolve_hierarchical("auto", 8) == 0
    with _config_mode("auto"):
        assert resolve_hierarchical(None, 8) == 0  # config default
    # The legacy boolean spelling is an explicit opt-in: it forces the
    # ladder over any tri-state default.
    saved = st.config.hierarchical_allreduce
    st.config.hierarchical_allreduce = True
    try:
        with _inner_size(2):
            for ambient in ("auto", "off"):
                with _config_mode(ambient):
                    assert resolve_hierarchical(None, 8) == 2
    finally:
        st.config.hierarchical_allreduce = saved
    with pytest.raises(InvalidArgumentError):
        resolve_hierarchical("sometimes", 8)


class _FakeDev:
    """Minimal device stand-in for topology-detection tests (the CPU
    harness cannot fabricate multi-slice/ragged device sets)."""

    def __init__(self, i, process_index=0, slice_index=None):
        self.id = i
        self.process_index = process_index
        self.slice_index = slice_index


def test_auto_degrades_flat_on_heterogeneous_topology(hvd):
    """Default auto mode on a RAGGED chips-per-domain layout (3+5): no
    valid ladder tiling exists, so resolve must degrade to flat (the
    reference's is_homogeneous rule) instead of raising out of every
    DistributedOptimizer trace."""
    st = _state.global_state()
    ragged = ([_FakeDev(i, process_index=0) for i in range(3)]
              + [_FakeDev(3 + i, process_index=1) for i in range(5)])
    from horovod_tpu.parallel.mesh import dcn_present

    assert dcn_present(ragged)  # heterogeneous counts as multi-domain
    saved = st.devices
    st.devices = ragged
    try:
        with _config_mode("auto"):
            assert resolve_hierarchical("auto", 8) == 0
            assert resolve_hierarchical(None, 8) == 0
            # An explicit inner size still engages (the escape hatch).
            with _inner_size(4):
                assert resolve_hierarchical("auto", 8) == 4
    finally:
        st.devices = saved


def test_auto_engages_on_multi_slice_topology(hvd):
    """Default auto mode on a clean 2-slice x 4-chip set resolves to
    the detected chips-per-slice — the zero-config multi-slice story."""
    st = _state.global_state()
    slices = [_FakeDev(i, slice_index=i // 4) for i in range(8)]
    saved = st.devices
    st.devices = slices
    try:
        with _config_mode("auto"):
            assert resolve_hierarchical("auto", 8) == 4
            assert resolve_hierarchical(None, 8) == 4
    finally:
        st.devices = saved


def test_hybrid_mesh_rejects_ici_axis_spanning_slices(hvd):
    """hybrid_mesh contract: on a REAL multi-slice device set, ICI axes
    must tile exactly one slice — an ICI product crossing the DCN
    boundary (which would run the ladder's 'fast' legs over the slow
    fabric) raises instead of silently building. Single-domain sets
    (the CPU virtual testing path) may factor freely."""
    from horovod_tpu.parallel.mesh import hybrid_mesh

    two_slices = [_FakeDev(i, slice_index=i // 2) for i in range(4)]
    with pytest.raises(InvalidArgumentError, match="DCN boundary"):
        hybrid_mesh(ici_axes={"ici": 4}, dcn_axes={"dcn": 1},
                    devices=two_slices)
    mesh = hybrid_mesh(devices=two_slices)  # detected 2x2 builds
    assert mesh.devices.shape == (2, 2)
    assert mesh.axis_names == ("dcn", "ici")
    # Virtual factorization of a single-domain set stays allowed.
    import jax

    mesh = hybrid_mesh(ici_axes={"ici": 2}, dcn_axes={"dcn": 4},
                       devices=list(jax.devices()))
    assert mesh.devices.shape == (4, 2)


# -------------------------------------- quantized wire: exactness pins


@pytest.mark.parametrize("inner", [4, 2])
@pytest.mark.parametrize("comp_name", ["int8", "fp8"])
def test_quantized_average_no_double_scaling(hvd, inner, comp_name):
    """The dtype-ladder contract (fusion.py satellite): int8/fp8 composes
    with Average WITHOUT double-scaling. On quantization-grid data
    (every post-reduce-scatter value in {-A, 0, +A}, one magnitude per
    shard) the absmax-scaled codec round-trips exactly, so the
    hierarchical quantized Average must BIT-match the flat fp32 Average
    — any double divide (or mis-applied scale) shows up as an 8x/128x
    error, not noise."""
    rng = np.random.RandomState(5)
    bases = [np.asarray(rng.randint(-1, 2, size=s), np.float32)
             for s in _SHAPES]
    comp = getattr(hvd.Compression, comp_name)

    def fn(hierarchical, compression, inner_sz):
        def inner_fn():
            # Every rank contributes the SAME tensor: all reduction
            # stages see a single magnitude per shard -> exact codec.
            ts = [np.asarray(b) for b in bases]
            return tuple(fused_reduce(ts, average=True,
                                      compression=compression,
                                      fusion_threshold=_THRESHOLD,
                                      hierarchical=hierarchical))
        with _inner_size(inner_sz):
            return [np.asarray(o) for o in hvd.spmd_run(inner_fn)]

    ref = fn("off", hvd.Compression.none, 0)
    got = fn("on", comp, inner)
    for b, r, g in zip(bases, ref, got):
        np.testing.assert_array_equal(r, b)  # Average of n copies = b
        np.testing.assert_array_equal(g, r)


@pytest.mark.parametrize("inner", [4, 2])
def test_int8_random_data_close_and_sum_mode(hvd, inner):
    """Random data: the quantized hierarchical result tracks the flat
    result within codec tolerance in BOTH Average and Sum modes (a
    double-scale or missed divide would be off by 8x)."""
    bases = _bases(seed=7)
    for average in (True, False):
        ref = _run(bases, hierarchical="off", inner=0, average=average)
        got = _run(bases, hierarchical="on", inner=inner, average=average,
                   compression=hvd.Compression.int8)
        for r, g in zip(ref, got):
            scale = max(1.0, float(np.max(np.abs(r))))
            assert float(np.max(np.abs(r - g))) < 0.05 * scale, (
                average, float(np.max(np.abs(r - g))), scale)


def test_quantizer_without_hier_is_lossless(hvd):
    """int8/fp8 compress only the DCN leg; with no hierarchical ladder
    engaged there is nothing to compress — the flat path must be
    bit-identical to Compression.none."""
    bases = _bases(seed=8)
    ref = _run(bases, hierarchical="off", inner=0)
    got = _run(bases, hierarchical="off", inner=0,
               compression=hvd.Compression.int8)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


# --------------------------------------------- error-feedback residuals


def _ef_run_factory(inner, comp, bases):
    import jax
    import jax.numpy as jnp

    leaves = [jax.ShapeDtypeStruct(b.shape, jnp.float32) for b in bases]
    specs = ef_residual_specs(leaves, _THRESHOLD, 8, inner)
    res0 = tuple(jnp.zeros(s.shape, s.dtype) for s in specs)
    res_spec = tuple(P("hvd") for _ in res0)

    def step(res):
        ts = [jnp.asarray(b) * (hvd.rank() + 1).astype(jnp.float32)
              for b in bases]
        out, new_res = fused_reduce(
            ts, average=True, compression=comp,
            fusion_threshold=_THRESHOLD, hierarchical="on",
            residuals=res)
        return tuple(out), new_res

    with _inner_size(inner):
        run = hvd.spmd_fn(step, in_specs=(res_spec,),
                          out_specs=((P(),) * len(bases), res_spec))
    return run, res0


@pytest.mark.parametrize("inner", [4, 2])
def test_error_feedback_time_average_converges(hvd, inner):
    """The EF contract (1-bit SGD / DGC): with a FIXED gradient, the
    per-step quantized output has bounded error but the running MEAN of
    outputs converges to the true average — the residual re-injects
    exactly what the wire dropped. Feedback-free quantization keeps a
    constant bias instead."""
    bases = [b * 0.37 for b in _bases(seed=9)]  # off the quant grid
    true = [sum(r + 1 for r in range(8)) / 8.0 * b for b in bases]
    run, res = _ef_run_factory(inner, hvd.Compression.int8, bases)
    with _inner_size(inner):
        acc = [np.zeros_like(b) for b in bases]
        first_err = last_err = None
        steps = 10
        for it in range(steps):
            out, res = run(res)
            for a, o in zip(acc, out):
                a += np.asarray(o)
            err = max(float(np.max(np.abs(a / (it + 1) - t)))
                      for a, t in zip(acc, true))
            if it == 0:
                first_err = err
            last_err = err
    assert last_err < 0.35 * first_err, (first_err, last_err)
    # Residuals are rank-local per-chip shards of the declared specs.
    expected = [s.shape for s in ef_residual_specs(
        [np.zeros(s, np.float32) for s in _SHAPES], _THRESHOLD, 8,
        inner)]
    assert [r.shape for r in res] == expected


def test_ef_exact_codec_leaves_zero_residual(hvd):
    """On quantization-grid data the codec round-trips exactly up to
    one ulp of the scale division (absmax/127 is not a power of two),
    so the residual (wire error in the SUM domain) must come back at
    ulp level — orders below the ~1% real quantization error — AND the
    output must bit-equal the true average: error feedback composes
    with Average without touching the result when there is no error to
    feed back."""
    rng = np.random.RandomState(11)
    bases = [np.asarray(rng.randint(-1, 2, size=s), np.float32)
             for s in _SHAPES]
    import jax
    import jax.numpy as jnp

    leaves = [jax.ShapeDtypeStruct(b.shape, jnp.float32) for b in bases]
    res0 = tuple(jnp.zeros(s.shape, s.dtype)
                 for s in ef_residual_specs(leaves, _THRESHOLD, 8, 4))
    res_spec = tuple(P("hvd") for _ in res0)

    def step(res):
        ts = [jnp.asarray(b) for b in bases]  # same on every rank
        out, new_res = fused_reduce(
            ts, average=True, compression=hvd.Compression.int8,
            fusion_threshold=_THRESHOLD, hierarchical="on",
            residuals=res)
        return tuple(out), new_res

    with _inner_size(4):
        run = hvd.spmd_fn(step, in_specs=(res_spec,),
                          out_specs=((P(),) * len(bases), res_spec))
        out, res = run(res0)
    for b, o in zip(bases, out):
        np.testing.assert_array_equal(np.asarray(o), b)
    for r in res:
        assert float(np.max(np.abs(np.asarray(r)))) < 1e-6


def test_ef_residual_structure_validation(hvd):
    """A residual tuple that does not match the plan fails loudly with
    the rebuild hint (stale after a threshold/world/inner change)."""
    bases = _bases()

    def fn():
        import jax.numpy as jnp

        ts = [jnp.asarray(b) for b in bases]
        return fused_reduce(ts, average=True,
                            compression=hvd.Compression.int8,
                            fusion_threshold=_THRESHOLD,
                            hierarchical="on",
                            residuals=(np.zeros((3,), np.float32),))[0]

    with _inner_size(4):
        with pytest.raises(InvalidArgumentError, match="ef_residual_specs"):
            hvd.spmd_run(fn)


def test_ef_residuals_with_flat_resolution_fail_loudly(hvd):
    """EF residuals present + a quantizing compressor, but the ladder
    resolves FLAT on this axis (init-world vs trace-axis drift, e.g.
    inner == axis size): silently skipping the quantized exchange would
    let fp32 flow while the user believes int8 EF is active — must
    raise with the re-init hint, not pass through."""
    import jax.numpy as jnp

    bases = _bases()

    def fn():
        ts = [jnp.asarray(b) for b in bases]
        return fused_reduce(ts, average=True,
                            compression=hvd.Compression.int8,
                            fusion_threshold=_THRESHOLD,
                            hierarchical="on",
                            residuals=(jnp.zeros((8,), jnp.float32),))[0]

    with _inner_size(8):  # inner == axis size -> ladder degrades flat
        with pytest.raises(InvalidArgumentError,
                           match="resolves to FLAT"):
            hvd.spmd_run(fn)


def test_ef_residuals_on_eager_lane_fail_loudly(hvd):
    """Multi-process eager lane (no SPMD axis): there is no
    hierarchical/quantized exchange, so EF residuals + a quantizing
    compressor must raise instead of silently allreducing full
    precision while the state says int8 is active."""
    import jax.numpy as jnp

    st = _state.global_state()
    saved = st.process_count
    st.process_count = 2
    try:
        with pytest.raises(InvalidArgumentError, match="eager lane"):
            fused_reduce([jnp.ones((4,))], average=True,
                         compression=hvd.Compression.int8,
                         hierarchical="on",
                         residuals=(jnp.zeros((2,), jnp.float32),))
    finally:
        st.process_count = saved


def test_residuals_pass_through_when_disengaged(hvd):
    """With the ladder off (or no quantizer) residuals flow through
    untouched — a caller can thread state unconditionally."""
    import jax.numpy as jnp

    bases = _bases()
    marker = (jnp.full((7,), 3.25, jnp.float32),)

    def fn():
        ts = [jnp.asarray(b) for b in bases]
        out, res = fused_reduce(ts, average=True,
                                fusion_threshold=_THRESHOLD,
                                hierarchical="off", residuals=marker)
        return tuple(out) + tuple(res)

    outs = hvd.spmd_run(fn)
    np.testing.assert_array_equal(np.asarray(outs[-1]),
                                  np.asarray(marker[0]))


# ------------------------------------ DistributedOptimizer + train step


def test_distributed_optimizer_hier_none_wiring(hvd):
    """The full user wiring at Compression.none: one SPMD training
    step's parameters with the ladder on vs off. Bit-exactness of the
    exchange itself is pinned by test_hier_matches_flat_bitexact on
    integer-valued data (where every summation order is exact); real
    model gradients are arbitrary floats and the ladder legally
    re-associates the cross-rank sum (8 = 2x4 tree vs XLA's flat
    order), so THIS pin asserts ulp-level closeness — anything beyond
    reassociation noise (a dropped shard, a double divide) is orders
    louder."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models
    from horovod_tpu.jax.optimizer import DistributedOptimizer

    rng = np.random.RandomState(3)
    shard_img = rng.randint(0, 2, (2, 28, 28, 1)).astype(np.float32)
    shard_lab = rng.randint(0, 10, (2,))

    def step_params(hierarchical, inner):
        model = models.MNISTNet()
        state, _ = models.create_train_state(
            jax.random.PRNGKey(0), model, optax.sgd(0.125, momentum=0.5),
            jnp.zeros((1, 28, 28, 1)))
        with _inner_size(inner):
            opt = DistributedOptimizer(optax.sgd(0.125, momentum=0.5),
                                       fusion_threshold=4096,
                                       hierarchical=hierarchical)
            state["opt_state"] = opt.init(state["params"])

            def step(state, batch):
                # Deterministic eval-mode forward (no dropout): with the
                # replicated batch, every rank's gradient is identical.
                def loss_fn(params):
                    logits = model.apply(
                        {"params": params,
                         "batch_stats": state["batch_stats"]},
                        batch["image"], train=False)
                    return models.cross_entropy_loss(
                        logits, batch["label"])

                grads = jax.grad(loss_fn)(state["params"])
                return models.apply_gradients(opt, state, grads)

            batch = {"image": jnp.asarray(np.tile(shard_img, (8, 1, 1, 1))),
                     "label": jnp.asarray(np.tile(shard_lab, 8))}
            new_state = hvd.spmd_run(step, state, batch,
                                     in_specs=(P(), P("hvd")),
                                     out_specs=P())
        return jax.tree_util.tree_leaves(new_state["params"])

    ref = step_params("off", 0)
    for inner in (4, 2):
        got = step_params("on", inner)
        assert len(ref) == len(got)
        for r, g in zip(ref, got):
            np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                       rtol=1e-6, atol=1e-7)


def test_distributed_optimizer_int8_ef_state_wiring(hvd):
    """create_train_state(compression=int8, hierarchical=on) carries
    rank-local EF residuals in the optimizer state;
    state_partition_specs maps them to P("hvd"); two steps run with a
    stable state structure and the residuals become nonzero."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models
    from horovod_tpu.jax.optimizer import _AllreduceState

    with _inner_size(4):
        model = models.MNISTNet()
        state, opt = models.create_train_state(
            jax.random.PRNGKey(0), model, optax.sgd(0.1, momentum=0.9),
            jnp.zeros((1, 28, 28, 1)),
            compression=hvd.Compression.int8, hierarchical="on")
        spec = models.state_partition_specs(state)
        step = models.make_train_step(model, opt, average_loss=False)
        rng = np.random.RandomState(3)
        batch = {"image": jnp.asarray(
            rng.rand(16, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(rng.randint(0, 10, (16,)))}
        s1, _ = hvd.spmd_run(step, state, batch,
                             in_specs=(spec, P("hvd")),
                             out_specs=(spec, P()))
        s2, _ = hvd.spmd_run(step, s1, batch,
                             in_specs=(spec, P("hvd")),
                             out_specs=(spec, P()))

    def residuals_of(tree):
        found = []

        def visit(node):
            if isinstance(node, _AllreduceState):
                found.extend(node.residuals)
            return node

        jax.tree_util.tree_map(
            visit, tree,
            is_leaf=lambda n: isinstance(n, _AllreduceState))
        return found

    res0 = residuals_of(state["opt_state"])
    res2 = residuals_of(s2["opt_state"])
    assert res0 and len(res0) == len(res2)
    assert all(float(jnp.max(jnp.abs(r))) == 0 for r in res0)
    assert any(float(jnp.max(jnp.abs(r))) > 0 for r in res2)
    assert (jax.tree_util.tree_structure(state)
            == jax.tree_util.tree_structure(s2))


# ------------------------------------------ EF convergence on a small LM


def _lm_loss_history(wire, inner, steps=24, feedback=True):
    """Train a tiny LM under DP for ``steps`` with the given DCN wire
    ("none" = fp32 flat reference); returns the loss trajectory."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models

    comp = getattr(hvd.Compression, wire)
    quantized = wire in ("int8", "fp8")
    model = models.TransformerLM(vocab_size=64, num_layers=2,
                                 num_heads=2, embed_dim=32, max_len=32)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = model.init(rng, sample, train=False)["params"]
    opt = optax.sgd(0.3)
    opt_state = opt.init(params)
    leaves = jax.tree_util.tree_leaves(params)
    threshold = 16 * 1024  # several buckets over the tiny LM tree
    if quantized and feedback:
        res = tuple(jnp.zeros(s.shape, s.dtype) for s in
                    ef_residual_specs(leaves, threshold, 8, inner))
    else:
        res = None

    use_ef = res is not None

    def step(params, opt_state, res, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens, train=False)
            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(logp, tgt[..., None], -1)
            return jnp.mean(nll)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        kwargs = dict(average=True, compression=comp,
                      fusion_threshold=threshold,
                      hierarchical="on" if quantized else "off")
        if use_ef:
            red, new_res = fused_reduce(g_leaves, residuals=res, **kwargs)
        else:
            red, new_res = fused_reduce(g_leaves, **kwargs), ()
        grads = jax.tree_util.tree_unflatten(treedef, red)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, new_res, hvd.allreduce(loss)

    res_spec = tuple(P("hvd") for _ in (res or ()))
    with _inner_size(inner if quantized else 0):
        run = hvd.spmd_fn(
            step,
            in_specs=(P(), P(), res_spec, P("hvd")),
            out_specs=(P(), P(), res_spec, P()))
        data_rng = np.random.RandomState(0)
        losses = []
        res_in = res if res is not None else ()
        for it in range(steps):
            tokens = jnp.asarray(
                data_rng.randint(0, 64, (16, 16)), jnp.int32)
            params, opt_state, res_in, loss = run(
                params, opt_state, res_in, tokens)
            losses.append(float(loss))
    return np.asarray(losses)


def test_ef_convergence_small_lm(hvd):
    """The convergence pin (ISSUE satellite): on a small LM under DP,
    the fp8-quantized-DCN loss trajectory with error feedback stays
    within tolerance of the fp32 trajectory, and is STRICTLY closer to
    it than feedback-free quantization — the error-feedback residual is
    what keeps low-bit wire compression from biasing training."""
    ref = _lm_loss_history("none", 0)
    ef = _lm_loss_history("fp8", 2, feedback=True)
    noef = _lm_loss_history("fp8", 2, feedback=False)
    dev_ef = float(np.mean(np.abs(ef - ref)))
    dev_noef = float(np.mean(np.abs(noef - ref)))
    # Within tolerance of fp32 DP...
    assert dev_ef < 0.05 * float(np.mean(ref)), (dev_ef, ref.mean())
    assert abs(ef[-1] - ref[-1]) < 0.05 * ref[-1], (ef[-1], ref[-1])
    # ...and strictly better than quantization without feedback.
    assert dev_ef < dev_noef, (dev_ef, dev_noef)


# -------------------------------------------------- static wire summary


def test_hier_wire_summary_accounting(hvd):
    """The bench "wire" stamp's math: per-leg operand bytes derived from
    the same hier_bucket_layout the executing path uses. DCN bytes must
    be <= 1/inner of the flat-psum bytes, and ~4x less again under
    int8."""
    import jax
    import jax.numpy as jnp

    leaves = [jax.ShapeDtypeStruct(s, jnp.float32) for s in _SHAPES]
    plan = plan_buckets(leaves, _THRESHOLD)
    flat_bytes = sum(b.nbytes for b in plan)
    for inner in (4, 2):
        none = hier_wire_summary(plan, 8, inner)
        q = hier_wire_summary(plan, 8, inner, hvd.Compression.int8)
        # Uncompressed DCN leg: exactly the (padded) shard bytes.
        assert flat_bytes / inner <= none["dcn_bytes"] \
            <= flat_bytes / inner + 8 * 4 * len(plan)
        assert none["ratio"] == 1.0 and none["dtype"] == "float32"
        # int8 leg: ~4x below that (plus scale scalars / sub-shard leg).
        assert q["dcn_bytes"] < none["dcn_bytes"] / 2
        assert q["dtype"] == "int8" and q["ratio"] > 2.5
        # ICI legs stay at the input dtype — identical up to the
        # two-stage padding quantum (inner*m elements per bucket).
        m = 8 // inner
        slack = inner * m * 4 * 2 * len(plan)
        assert none["ici_bytes"] <= q["ici_bytes"] \
            <= none["ici_bytes"] + slack


def test_hier_layout_matches_ef_specs(hvd):
    """hier_bucket_layout and ef_residual_specs agree on shard/sub
    geometry (one layout, many consumers)."""
    import jax
    import jax.numpy as jnp

    leaves = [jax.ShapeDtypeStruct(s, jnp.float32) for s in _SHAPES]
    for inner in (4, 2):
        specs = ef_residual_specs(leaves, _THRESHOLD, 8, inner)
        expect = []
        for b in plan_buckets(leaves, _THRESHOLD):
            layout = hier_bucket_layout(b.nbytes // 4, 8, inner,
                                        quantized=True)
            expect.append((8 * layout["shard_elems"],))
            if layout["two_stage"]:
                expect.append((8 * layout["sub_elems"],))
        assert [s.shape for s in specs] == expect
