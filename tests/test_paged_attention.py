"""Fused paged-attention decode kernel exactness + accounting
(horovod_tpu/ops/paged_attention.py).

The kernel-level half of the PR-8 acceptance matrix: interpret-mode
execution against the serving engine's own gather reference
(``_gather_cache`` + ``dot_product_attention(q_offset=t)``) across
ragged lengths, page-boundary edges, single-page requests, idle lanes,
and physically-shuffled page tables — with the reserved null page 0
POISONED with NaN, so any read of its contents into an attention sum
fails loudly instead of averaging in silently. The engine-level token
pins live in tests/test_serve_engine.py (attention-parametrized).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.attention import dot_product_attention
from horovod_tpu.ops.paged_attention import (
    paged_attention_decode,
    paged_grid_info,
)
from horovod_tpu.serve.engine import _gather_cache

H, D = 2, 8


def _case(lengths, ps, pps, seed=0, shuffle=False):
    """Pages + tables for the given per-slot live-key counts. The null
    page 0 is NaN-poisoned; each live slot's first ceil(len/ps) table
    entries map distinct real pages (the engine's ensure_pages
    invariant), the tail stays 0 (unmapped -> null)."""
    rng = np.random.default_rng(seed)
    S = len(lengths)
    need = [-(-int(x) // ps) for x in lengths]
    P = 1 + sum(need) + 2                      # a couple of free pages
    k_pages = rng.normal(size=(P, ps, H, D)).astype(np.float32)
    v_pages = rng.normal(size=(P, ps, H, D)).astype(np.float32)
    k_pages[0] = np.nan
    v_pages[0] = np.nan
    ids = list(range(1, P))
    if shuffle:
        rng.shuffle(ids)
    tables = np.zeros((S, pps), np.int32)
    nxt = 0
    for s, n in enumerate(need):
        for j in range(n):
            tables[s, j] = ids[nxt]
            nxt += 1
    q = rng.normal(size=(S, H, D)).astype(np.float32)
    return q, k_pages, v_pages, tables, np.asarray(lengths, np.int32)


def _reference(q, k_pages, v_pages, tables, lengths):
    """The engine's gather path, slot by slot: reconstruct the dense
    logical cache through the page table, attend with q_offset = t
    (the cache mask — unwritten and null-page rows masked)."""
    S = q.shape[0]
    scale = 1.0 / math.sqrt(D)
    outs = []
    for s in range(S):
        ln = int(lengths[s])
        if ln == 0:
            outs.append(np.zeros((H, D), np.float32))
            continue
        gk = _gather_cache(jnp.asarray(k_pages), jnp.asarray(tables[s]))
        gv = _gather_cache(jnp.asarray(v_pages), jnp.asarray(tables[s]))
        # Slice to the live keys (in the engine the masked tail is
        # zeros and the causal mask makes it weightless; here it is
        # NaN-poisoned, and the reference einsum's 0 * NaN would
        # poison the row the kernel correctly never reads).
        out = dot_product_attention(
            jnp.asarray(q[s])[None], gk[:ln], gv[:ln], causal=True,
            scale=scale, q_offset=ln - 1)
        outs.append(np.asarray(out)[0])
    return np.stack(outs)


def _run(q, k_pages, v_pages, tables, lengths):
    return np.asarray(paged_attention_decode(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(tables), jnp.asarray(lengths)))


def _check(lengths, ps, pps, **kw):
    q, kp, vp, tab, lens = _case(lengths, ps, pps, **kw)
    out = _run(q, kp, vp, tab, lens)
    ref = _reference(q, kp, vp, tab, lens)
    assert np.isfinite(out).all(), "null-page NaN leaked into a sum"
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    return out


class TestKernelExactness:
    def test_ragged_lengths(self):
        """Lengths straddling every page-fill state, null page NaN:
        mid-page, full page, page+1, single row, idle lane."""
        _check([7, 8, 9, 1, 0, 3], ps=4, pps=4)

    def test_length_exactly_on_page_boundary(self):
        _check([4, 8, 12], ps=4, pps=3)

    def test_single_page_requests(self):
        """pps == 1: the whole logical cache is one page."""
        _check([1, 2, 4], ps=4, pps=1)

    def test_table_tail_never_touched(self):
        """A table far longer than any request (the 'Lmax >> t' regime
        the kernel exists for): the unmapped null tail is never
        streamed — proven by the NaN poison."""
        _check([3, 5], ps=4, pps=16)

    def test_physically_shuffled_pages(self):
        """Physical discontiguity is invisible: pages allocated in
        shuffled order give the identical result."""
        q, kp, vp, tab, lens = _case([7, 9, 2], ps=4, pps=4,
                                     shuffle=True)
        out = _run(q, kp, vp, tab, lens)
        np.testing.assert_allclose(
            out, _reference(q, kp, vp, tab, lens), rtol=1e-5, atol=1e-5)

    def test_idle_lane_outputs_zeros(self):
        q, kp, vp, tab, lens = _case([5, 0, 0], ps=4, pps=2)
        out = _run(q, kp, vp, tab, lens)
        assert np.all(out[1:] == 0.0)

    def test_garbage_rows_past_t_in_last_page_ignored(self):
        """Rows of the last live page beyond position t are allocated
        but unwritten — after LIFO page reuse they hold STALE finite
        values from an evicted request. Poison them huge and pin that
        their weight is exactly zero (the mask runs BEFORE the running
        max, so a 1e30 garbage score can never shift the softmax
        statistics either)."""
        q, kp, vp, tab, lens = _case([6], ps=4, pps=2)
        ref = _reference(q, kp, vp, tab, lens)
        kp[tab[0, 1], 2:] = 1e30           # rows 6..7 of page slot 1
        vp[tab[0, 1], 2:] = 1e30
        out = _run(q, kp, vp, tab, lens)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_shape_mismatches_raise(self):
        q, kp, vp, tab, lens = _case([4], ps=4, pps=2)
        with pytest.raises(ValueError, match="shape mismatch"):
            paged_attention_decode(jnp.asarray(q),
                                   jnp.asarray(kp[:, :, :, :4]),
                                   jnp.asarray(vp), jnp.asarray(tab),
                                   jnp.asarray(lens))
        with pytest.raises(ValueError, match="slots"):
            paged_attention_decode(jnp.asarray(q), jnp.asarray(kp),
                                   jnp.asarray(vp), jnp.asarray(tab),
                                   jnp.asarray(np.zeros(3, np.int32)))


class TestPagedGridInfo:
    def test_pages_live_is_ceil(self):
        info = paged_grid_info([7, 8, 9, 1, 0], page_size=4,
                               pages_per_seq=4, num_heads=H, head_dim=D)
        assert info["pages_live"] == [2, 2, 3, 1, 0]
        assert info["pages_live_total"] == 8
        assert info["pages_full_total"] == 20
        assert info["kv_fetch_frac"] == 0.4

    def test_bytes_accounting(self):
        info = paged_grid_info([4], page_size=4, pages_per_seq=8,
                               num_heads=H, head_dim=D, dtype_bytes=4,
                               num_layers=3)
        tile = 2 * 4 * H * D * 4 * 3
        assert info["kv_bytes"] == tile
        assert info["kv_bytes_gather"] == 8 * tile
        assert info["kv_fetch_frac"] == round(1 / 8, 4)

    def test_visited_pages_exclude_null(self):
        """The 'null page never read' pin: the physical pages the
        kernel's index map streams for LIVE slots never include the
        reserved page 0, and idle lanes visit nothing."""
        _, _, _, tab, lens = _case([7, 4, 0], ps=4, pps=4)
        info = paged_grid_info(lens, page_size=4, pages_per_seq=4,
                               num_heads=H, head_dim=D, tables=tab)
        assert info["pages_visited"][0] == list(tab[0, :2])
        assert info["pages_visited"][2] == []
        assert all(0 not in v for v in info["pages_visited"])

    def test_overflow_and_negative_raise(self):
        with pytest.raises(ValueError, match="exceeds the page table"):
            paged_grid_info([17], page_size=4, pages_per_seq=4,
                            num_heads=H, head_dim=D)
        with pytest.raises(ValueError, match="negative"):
            paged_grid_info([-1], page_size=4, pages_per_seq=4,
                            num_heads=H, head_dim=D)
