"""Model zoo + training-step tests.

Ports the reference's gradient/optimizer test strategy (SURVEY §4: expected
grads compared to closed forms, test_torch.py:377-429; end-to-end DP step)
onto the 8-device virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu import models


@pytest.fixture(scope="module")
def rng():
    return jax.random.PRNGKey(0)


def test_resnet_family_builds():
    for name in ["resnet18", "resnet34", "resnet50"]:
        m = models.build(name, num_classes=7)
        assert m.num_classes == 7
    with pytest.raises(ValueError):
        models.build("resnet99")


def test_resnet_forward_shape(rng):
    model = models.ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_mnist_forward_shape(rng):
    model = models.MNISTNet()
    x = jnp.zeros((3, 28, 28, 1))
    variables = model.init(rng, x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (3, 10)


def test_train_step_single_process(hvd, rng):
    """size()==1 degradation: the same step runs eagerly under plain jit."""
    model = models.MNISTNet()
    state, opt = models.create_train_state(
        rng, model, optax.adam(1e-3), jnp.zeros((1, 28, 28, 1))
    )
    step = jax.jit(models.make_train_step(model, opt))
    batch = {
        "image": jax.random.normal(rng, (8, 28, 28, 1)),
        "label": jax.random.randint(rng, (8,), 0, 10),
    }
    losses = []
    for _ in range(10):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert int(state["step"]) == 10
    # Learns the fixed batch (dropout keeps it noisy; compare min to start).
    assert min(losses[3:]) < losses[0]


def test_train_step_spmd_matches_large_batch(hvd, rng):
    """DP invariance: N ranks at batch B/N with averaged grads == 1 rank at
    batch B (the contract behind the reference's lr × size scaling advice,
    reference docs; exact for sum-based losses)."""
    model = models.MNISTNet()
    # Dropout off for determinism: eval-style apply inside a custom loss.
    state, opt = models.create_train_state(
        rng, model, optax.sgd(0.1), jnp.zeros((1, 28, 28, 1))
    )

    def loss_fn(params, batch):
        logits = model.apply(
            {"params": params, "batch_stats": state["batch_stats"]},
            batch["image"],
            train=False,
        )
        return models.cross_entropy_loss(logits, batch["label"])

    batch = {
        "image": jax.random.normal(rng, (16, 28, 28, 1)),
        "label": jax.random.randint(rng, (16,), 0, 10),
    }

    # Single-device reference grads on the full batch.
    ref_grads = jax.grad(loss_fn)(state["params"], batch)

    # SPMD: each rank grads its shard, DistributedOptimizer-style average.
    def spmd_grads(params, batch):
        g = jax.grad(loss_fn)(params, batch)
        from horovod_tpu.jax.fusion import fused_reduce

        leaves, treedef = jax.tree_util.tree_flatten(g)
        return jax.tree_util.tree_unflatten(treedef, fused_reduce(leaves, average=True))

    got = hvd.spmd_run(
        spmd_grads, state["params"], batch, in_specs=(P(), P("hvd")), out_specs=P()
    )
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads), jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)


def test_full_spmd_train_step(hvd, rng):
    model = models.ResNet18(num_classes=10, dtype=jnp.float32)
    state, opt = models.create_train_state(
        rng, model, optax.sgd(0.1), jnp.zeros((1, 32, 32, 3))
    )
    step = models.make_train_step(model, opt)
    batch = {
        "image": jax.random.normal(rng, (16, 32, 32, 3)),
        "label": jax.random.randint(rng, (16,), 0, 10),
    }
    state, metrics = hvd.spmd_run(
        step, state, batch, in_specs=(P(), P("hvd")), out_specs=(P(), P())
    )
    assert int(state["step"]) == 1
    assert np.isfinite(float(metrics["loss"]))


def test_graft_entry_dryrun():
    import __graft_entry__ as g

    fn, args = g.entry()
    jax.eval_shape(fn, *args)  # traceable without a real forward


def test_graft_entry_multichip_subprocess():
    """Run the driver's multichip gate end-to-end, exactly as the driver
    does: a fresh interpreter with NO env setup, calling
    ``dryrun_multichip(8)``. The entry point must self-provision the
    8-device virtual mesh (round-1 regression: it assumed devices existed)."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('MULTICHIP_OK')"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "MULTICHIP_OK" in proc.stdout


def test_graft_entry_gate_catches_broken_conjugate(hvd, monkeypatch):
    """The driver gate's closed-form asserts must catch a
    gradient-only bug: replace the Megatron ``g`` conjugate with one
    whose forward is identical (psum) but whose backward scales the
    cotangent by 1.25 — wrong in every gradient regime, invisible to a
    finite-loss check. The tp x sp x dp lane has to fail its
    dense-reference check, NOT sail through."""
    from functools import partial

    import __graft_entry__ as g
    from jax import lax

    from horovod_tpu.parallel import tp as tp_mod

    @partial(jax.custom_vjp, nondiff_argnums=(1,))
    def bad_output(x, axis):
        return lax.psum(x, axis)

    def _bad_fwd(x, axis):
        return lax.psum(x, axis), None

    def _bad_bwd(axis, _, grad):
        return (lax.pcast(grad * 1.25, axis, to="varying"),)

    bad_output.defvjp(_bad_fwd, _bad_bwd)
    monkeypatch.setattr(tp_mod, "tp_region_output", bad_output)
    with pytest.raises(AssertionError):
        g._dryrun_tp_sp_dp(8)


def test_eval_step(hvd, rng):
    model = models.MNISTNet()
    state, _ = models.create_train_state(
        rng, model, optax.sgd(0.1), jnp.zeros((1, 28, 28, 1))
    )
    ev = models.make_eval_step(model)
    batch = {
        "image": jax.random.normal(rng, (8, 28, 28, 1)),
        "label": jax.random.randint(rng, (8,), 0, 10),
    }
    out = jax.jit(ev)(state, batch)
    assert float(out["count"]) == 8.0
    assert 0 <= float(out["correct"]) <= 8


def test_bf16_momentum_tracks_fp32(hvd, rng):
    """Mixed-precision optimizer state (bench --bf16-momentum): keeping
    SGD momentum in bfloat16 halves the optimizer-state HBM traffic
    (PERF.md) and must track the fp32-momentum trajectory closely while
    the momentum leaves are actually stored in bf16."""
    model = models.MNISTNet()
    batch = {
        "image": jax.random.normal(rng, (16, 28, 28, 1)),
        "label": jax.random.randint(rng, (16,), 0, 10),
    }

    def train(accumulator_dtype):
        sgd = optax.sgd(0.05, momentum=0.9,
                        accumulator_dtype=accumulator_dtype)
        state, opt = models.create_train_state(
            rng, model, sgd, jnp.zeros((1, 28, 28, 1)))
        step = jax.jit(models.make_train_step(model, opt))
        losses = []
        for _ in range(15):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return state, losses

    state16, losses16 = train(jnp.bfloat16)
    state32, losses32 = train(None)

    momentum_dtypes = {
        leaf.dtype.name
        for leaf in jax.tree_util.tree_leaves(state16["opt_state"])
        if hasattr(leaf, "dtype") and leaf.ndim > 0
    }
    assert "bfloat16" in momentum_dtypes, momentum_dtypes
    # Early trajectory tracks within bf16 accumulation error (later steps
    # drift chaotically through dropout + nonconvexity, in either
    # direction), and the bf16 run still learns.
    np.testing.assert_allclose(losses16[:5], losses32[:5], rtol=0.1)
    assert min(losses16[5:]) < losses16[0]
    # Params stay fp32 (only the accumulator is quantized).
    p16 = jax.tree_util.tree_leaves(state16["params"])[0]
    assert p16.dtype == jnp.float32


def test_transformer_lm_trains_with_flash_attention(rng):
    """The pallas flash kernel plugs into TransformerLM's attn_fn hook
    AND trains (its custom-VJP backward): logits, loss, and one gradient
    step must match the dense-attention model."""
    import functools

    from horovod_tpu.ops.attention import flash_attention

    flash = functools.partial(flash_attention, causal=True, block_q=8,
                              block_k=8)
    kw = dict(vocab_size=32, num_layers=2, num_heads=2, embed_dim=16,
              max_len=32, dtype=jnp.float32)
    dense_m = models.TransformerLM(**kw)
    flash_m = models.TransformerLM(attn_fn=flash, **kw)

    tokens = jax.random.randint(rng, (2, 16), 0, 32)
    params = dense_m.init(rng, tokens, train=False)["params"]

    def loss_fn(model, params):
        logits = model.apply({"params": params}, tokens, train=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(
            logp[:, :-1], tokens[:, 1:, None], -1))

    # Same params work in both models (attn_fn is parameter-free).
    ld, gd = jax.value_and_grad(lambda p: loss_fn(dense_m, p))(params)
    lf, gf = jax.value_and_grad(lambda p: loss_fn(flash_m, p))(params)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_scan_layers_matches_unrolled(rng):
    """scan_layers compiles ONE weight-stacked block (lax.scan) instead
    of num_layers unrolled copies; per-layer math must be identical.
    Transplants the stacked params into the unrolled layout and pins
    logits AND gradients across the two layouts, plus the remat
    variants (which must be numerically a no-op)."""
    kw = dict(vocab_size=61, num_layers=3, num_heads=2, embed_dim=24,
              max_len=32, dtype=jnp.float32)
    scan_m = models.TransformerLM(scan_layers=True, **kw)
    unrl_m = models.TransformerLM(**kw)
    tokens = jax.random.randint(rng, (2, 16), 0, 61)

    ps = scan_m.init(rng, tokens, train=False)["params"]
    stacked = ps["layers"]["TransformerBlock_0"]
    pu = {k: v for k, v in ps.items() if k != "layers"}
    for i in range(kw["num_layers"]):
        pu[f"TransformerBlock_{i}"] = jax.tree.map(
            lambda a, i=i: a[i], stacked)

    def loss(model, params):
        logits = model.apply({"params": params}, tokens, train=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(
            logp[:, :-1], tokens[:, 1:, None], -1))

    ls, gs = jax.value_and_grad(lambda p: loss(scan_m, p))(ps)
    lu, gu = jax.value_and_grad(lambda p: loss(unrl_m, p))(pu)
    np.testing.assert_allclose(float(ls), float(lu), rtol=1e-6)

    # Gradients: restack the unrolled per-layer grads and compare.
    gu_stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[gu[f"TransformerBlock_{i}"] for i in range(kw["num_layers"])])
    for a, b in zip(jax.tree_util.tree_leaves(
            gs["layers"]["TransformerBlock_0"]),
            jax.tree_util.tree_leaves(gu_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for name in ["Embed_0", "Embed_1", "LayerNorm_0", "lm_head"]:
        for a, b in zip(jax.tree_util.tree_leaves(gs[name]),
                        jax.tree_util.tree_leaves(gu[name])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    # remat is a scheduling choice, not a numerical one.
    for scan in (True, False):
        m = models.TransformerLM(scan_layers=scan, remat=True, **kw)
        p = ps if scan else pu
        lr, gr = jax.value_and_grad(lambda q: loss(m, q))(p)
        np.testing.assert_allclose(float(lr), float(ls), rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(gr),
                        jax.tree_util.tree_leaves(
                            gs if scan else gu)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
