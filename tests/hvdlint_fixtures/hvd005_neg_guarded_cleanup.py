"""NEGATIVE: the guarded-cleanup idiom — the try exists to protect the
cleanup call itself (first statement of the body); there is nothing to
move into a finally. Also silent: a try body that repeats the cleanup in
its finally."""


def quiet_close(sock):
    try:
        sock.close()
    except OSError:
        pass


def stop_with_retry(server):
    try:
        server.drain()
        server.stop()
    finally:
        server.stop()
