"""NEGATIVE: the supported pattern — a handler exiting through the
run.driver taxonomy, by constant name or by its literal value. The
supervisor classifies 75 as *preempted* (free relaunch); the EXIT_*
name and the taxonomy literal both stay silent."""

import signal
import sys

EXIT_PREEMPTED = 75


class TaxonomyShutdown:
    def __init__(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self.triggered = True
        sys.exit(EXIT_PREEMPTED)


class LiteralTaxonomyShutdown:
    def __init__(self):
        signal.signal(signal.SIGUSR1, self._on_usr1)

    def _on_usr1(self, signum, frame):
        sys.exit(75)
