"""HVD013 negative: the refcounted discipline — every holder outside
the allocator's module drops pages through ``release()``, which
decrements and frees only at zero. Shared prefix pages survive their
first holder's teardown; exclusive pages free exactly as before.
"""


def teardown_request(cache, req):
    req.page_table[:] = 0
    cache.allocator.release(req.pages)
    req.pages.clear()


def reclaim_index_leaf(alloc, node):
    alloc.release([node.page])
    node.page = None
