"""POSITIVE: a locally-bound jitted callable dispatched inside a
perf_counter bracket with no sync — the shape of the pre-round-5 chip
probe (dispatch-only "TFLOP/s" stamps of 3,000-16,000 on a ~180 TF/s
chip). hvdlint tracks the ``jax.jit`` binding to know ``f`` dispatches.
"""

import time

import jax


def probe(fn, x, iters):
    f = jax.jit(fn)
    t0 = time.monotonic()
    y = x
    for _ in range(iters):
        y = f(y)
    elapsed = time.monotonic() - t0  # EXPECT: HVD001
    return elapsed / iters
