"""HVD014 negative: the chunk_stream discipline — every chunk carries
its own crc32, so a torn or bit-flipped chunk is a typed error at the
frame boundary, never a silent corruption. The digest identifier in
scope silences the rule."""

import struct
import zlib


def push_framed(sock, chunks):
    running = 0
    for c in chunks:
        crc = zlib.crc32(c) & 0xFFFFFFFF
        running = zlib.crc32(c, running) & 0xFFFFFFFF
        sock.sendall(struct.pack("<II", len(c), crc) + c)
    return running
