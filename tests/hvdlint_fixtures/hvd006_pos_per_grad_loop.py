"""POSITIVE: one allreduce per gradient from a Python loop — the
pattern the reference built its fusion buffer to kill
(operations.cc:2160-2264): every iteration pays a full collective
latency + dispatch where ``grouped_allreduce`` would pay once per
flat fusion-threshold bucket.
"""

import horovod_tpu.jax as hvd


def average_gradients(grads):
    reduced = []
    for g in grads:
        reduced.append(hvd.allreduce(g, average=True))  # EXPECT: HVD006
    return reduced


def sum_named_gradients(named_grads):
    out = {}
    for name, g in named_grads.items():
        out[name] = hvd.allreduce(g, average=False, name=name)  # EXPECT: HVD006
    return out
