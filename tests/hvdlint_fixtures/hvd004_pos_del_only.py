"""POSITIVE: resource release only in ``__del__`` — the Handle
fragility (VERDICT round-5 weak #6): under delayed GC or reference
cycles the resource (an in-flight op name, a file, a socket) stays
poisoned until collection, and interpreter teardown may skip the
finalizer entirely.
"""


class OpHandle:
    def __init__(self, name, registry):
        self.name = name
        self.registry = registry
        registry.add(name)

    def __del__(self):  # EXPECT: HVD004
        self.registry.discard(self.name)
