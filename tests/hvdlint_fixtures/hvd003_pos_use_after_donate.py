"""POSITIVE: use-after-donation — ``state`` is donated to the jitted
step (donate_argnums=(0,)) and then read afterwards. XLA has invalidated
the buffer; on hardware the read returns garbage or raises.
"""

import jax


def train(step, state, batch):
    f = jax.jit(step, donate_argnums=(0,))
    new_state = f(state, batch)
    checksum = state.params.sum()  # EXPECT: HVD003
    return new_state, checksum
