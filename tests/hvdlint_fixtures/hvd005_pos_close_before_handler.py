"""POSITIVE: mid-try ``close()`` with an except handler — when the
transfer raises, the handler runs and the socket leaks; the close
belongs in a finally."""


def send_all(make_socket, payload):
    sock = make_socket()
    try:
        sock.connect()
        sock.sendall(payload)
        sock.close()  # EXPECT: HVD005
    except OSError:
        return False
    return True
