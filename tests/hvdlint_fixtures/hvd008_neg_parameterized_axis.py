"""HVD008 negative: axis names flow in as PARAMETERS (the per-module
axes "tp"/"pp"/"sp"/"ep" already work this way) — no hardcoded
hvd/ici/dcn literal, nothing couples to the global spelling."""

from jax import lax


def all_mean(x, axis):
    return lax.psum(x, axis) / lax.axis_size(axis)


def tp_block(x, w, axis="tp"):
    return lax.psum(x @ w, axis)
