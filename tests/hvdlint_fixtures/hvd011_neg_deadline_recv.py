"""HVD011 negative: the transport discipline — every recv bounded.

A deadline parameter governs the whole frame and each recv runs under
an explicit socket timeout; a dead peer raises instead of hanging.
"""

import time


def read_exact(sock, n, deadline):
    buf = b""
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"{len(buf)}/{n} bytes")
        sock.settimeout(remaining)
        buf += sock.recv(n - len(buf))
    return buf
