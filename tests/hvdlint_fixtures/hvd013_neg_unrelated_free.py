"""HVD013 negative: ``free()`` on receivers that are not page
allocators — a buffer pool, a C-level handle — plus free-shaped
identifiers that never call through an allocator. The rule keys on
allocator-named receivers, not on the method name alone.
"""


def drop_buffer(pool, buf):
    pool.free(buf)           # a buffer pool, not a page allocator


def close_handle(handle):
    handle.free()            # C-level resource handle


def report(stats):
    return {"free": stats.available, "held": stats.held}
