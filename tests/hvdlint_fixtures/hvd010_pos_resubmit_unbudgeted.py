"""HVD010 positive: an unbudgeted request-resubmit loop. The except
arm swallows the overload error and immediately resubmits — the retry
storm shape: every rejected client hammers the service harder, and
nothing bounds or spaces the attempts."""


def send_until_accepted(router, request):
    while True:
        try:
            return router.resubmit(request)  # EXPECT: HVD010
        except OverloadedError:
            continue


class OverloadedError(Exception):
    pass
