"""POSITIVE: a collective under rank-divergent control flow — only rank 0
enters the allreduce, every other rank never joins the negotiation and
the job deadlocks (reference semantics: collectives are collective).
"""

import horovod_tpu.jax as hvd


def summarize(metrics):
    if hvd.rank() == 0:
        total = hvd.allreduce(metrics, average=True)  # EXPECT: HVD002
        return total
    return None


def gather_on_root(st, x):
    if st.process_index == 0:
        from horovod_tpu.jax import eager
        return eager.process_allgather(x)  # EXPECT: HVD002
    return x
