"""HVD011 positive: waiting on a worker's pipe with no bound.

A supervisor that readline()s a child's stdout for a readiness marker
hangs forever when the child dies before printing it — the
supervision loop never runs, the job never fails, the operator sees
nothing. The launcher's real pump threads are daemons that may block
by design (and say so); a control-path read like this must be bounded.
"""


def wait_for_ready(proc):
    while True:
        line = proc.stdout.readline()  # EXPECT: HVD011
        if b"READY" in line:
            return True
        if not line:
            return False
