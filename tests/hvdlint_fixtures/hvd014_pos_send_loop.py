"""HVD014 positive: a weights push that pumps raw chunks over a socket
with no per-chunk CRC and no deadline discipline anywhere in scope — a
stalled peer hangs the loop forever, and nothing downstream can tell a
torn stream from a finished one."""


def push_params(sock, blob, chunk_bytes):
    for off in range(0, len(blob), chunk_bytes):  # EXPECT: HVD014
        sock.sendall(blob[off:off + chunk_bytes])
