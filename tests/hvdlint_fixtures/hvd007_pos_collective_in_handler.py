"""POSITIVE: a blocking collective issued directly from a SIGTERM
handler. The signal interrupts arbitrary code — possibly a rank already
inside a negotiation — so the handler's own allreduce deadlocks the
coordinator exactly when the preemption grace window is ticking. The
supported pattern is defer-to-step-boundary (elastic/signals.py)."""

import signal

import horovod_tpu.jax as hvd


class EagerPreemptionSaver:
    def __init__(self, state):
        self.state = state
        signal.signal(signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        # "Just average the metrics before dying" — from handler context
        # this re-enters the collective machinery mid-negotiation.
        self.state["loss"] = hvd.allreduce(  # EXPECT: HVD007
            self.state["loss"], average=True)
