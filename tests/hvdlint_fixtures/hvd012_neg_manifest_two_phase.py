"""HVD012 negative: the elastic manifest's two-phase commit (the
canonical discipline, horovod_tpu/elastic/snapshot.py): the artifact
lands at a temp path first and os.replace() renames it into place
atomically — a crash between the phases leaves either the old
committed state or a stray .tmp, never a torn file at the path a
restore opens.
"""

import json
import os

import numpy as np


def commit_snapshot(directory, step, arrays, manifest):
    path = os.path.join(directory, f"snapshot-{step}.npz")
    tmp = f"{path}.{os.getpid()}.tmp"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)            # phase 1: the artifact commits
    pointer = os.path.join(directory, "MANIFEST")
    ptmp = f"{pointer}.{os.getpid()}.tmp"
    with open(ptmp, "w") as f:
        json.dump(manifest, f)
    os.replace(ptmp, pointer)        # phase 2: the pointer flips
