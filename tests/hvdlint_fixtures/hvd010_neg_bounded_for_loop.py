"""HVD010 negative: a bounded ``for`` retry loop, and a ``while True``
that loops over non-retry work (an event pump draining a queue). A
``for`` over a finite attempt range is already budgeted by
construction; a drain loop calling get()/process() retries nothing."""


def submit_with_retries(router, request, attempts=3):
    last = None
    for _ in range(attempts):
        try:
            return router.submit(request)
        except OSError as e:
            last = e
    raise last


def drain_events(queue):
    while True:
        event = queue.get()
        if event is None:
            return
        process(event)


def process(event):
    raise NotImplementedError
