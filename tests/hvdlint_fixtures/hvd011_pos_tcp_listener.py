"""HVD011 positive: a TCP listener that blocks forever in accept/recv.

The multi-host fleet round's shape: a worker whose accept() has no
timeout can never notice a shutdown flag, and its per-connection
recv() with no deadline hangs on a peer that dies mid-write — the
router sees a live process that serves nothing, with nothing for a
watchdog to classify. The real worker polls accept() in 0.25 s slices
and runs every recv through the deadline-sliced frame codec.
"""


def listener_loop(server_sock, handler):
    while True:
        conn, _ = server_sock.accept()  # EXPECT: HVD011
        handle_connection(conn, handler)


def handle_connection(conn, handler):
    header = conn.recv(12)  # EXPECT: HVD011
    handler(header)
