"""HVD013 positive: an eviction path dropping a victim's pages via the
bare allocator ``free()``.

The victim's prompt pages are exactly the ones most likely to be
shared — a prefix hit mapped them into a newer request's table, and
the radix index pins them with its own hold. Eviction must be
refcount-aware (``release()``): shared pages survive, exclusive ones
actually free.
"""


def evict_victim(alloc, victim):
    pages = list(victim.pages)
    victim.pages.clear()
    alloc.free(pages)  # EXPECT: HVD013
    return len(pages)
