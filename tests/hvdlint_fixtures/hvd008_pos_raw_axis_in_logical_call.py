"""HVD008 positive, post-LogicalMesh shape: a raw physical-axis literal
passed where a LOGICAL axis name is expected. ``LogicalMesh.spec`` and
``module_axis`` take logical names ("batch", "heads", ...) or role names
("data", "tensor", ...); smuggling the physical spelling back in
re-couples the call site to the mesh layout the rules table exists to
hide."""

from horovod_tpu.parallel.logical import LogicalMesh, module_axis


def batch_spec(lm: LogicalMesh):
    return lm.spec("hvd", None)  # EXPECT: HVD008


def data_axis():
    return module_axis("data", "hvd")  # EXPECT: HVD008
