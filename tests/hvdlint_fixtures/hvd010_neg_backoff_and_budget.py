"""HVD010 negative: the supervised-relaunch discipline — an attempt
counter compared against a budget AND a backoff sleep between
attempts (the elastic supervisor / serving fleet shape). Either signal
alone silences the rule; this fixture carries both."""

import time


def supervise(cmd, max_restarts):
    attempts = 0
    while True:
        result = relaunch_worker(cmd)
        if result.code == 0:
            return 0
        if attempts >= max_restarts:
            return result.code
        attempts += 1
        time.sleep(0.5 * (2 ** attempts))


def relaunch_worker(cmd):
    raise NotImplementedError
