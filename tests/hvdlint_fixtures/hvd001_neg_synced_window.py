"""NEGATIVE: the corrected round-5 discipline — a forced device sync
(block_until_ready / force_device_sync) inside the timed region. This is
bench.py's run_timed shape after the correction; hvdlint must stay
silent.
"""

import time

import jax

from horovod_tpu.utils.devsync import force_device_sync


def timed_window(run_step, state, batch, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        state, _ = run_step(state, batch)
    jax.block_until_ready(state)
    return iters / (time.perf_counter() - t0)


def timed_once(run_step, state, batch):
    t0 = time.perf_counter()
    state, metrics = run_step(state, batch)
    force_device_sync(state)
    return time.perf_counter() - t0
