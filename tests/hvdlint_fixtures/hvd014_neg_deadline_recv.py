"""HVD014 negative: a reassembly loop under deadline discipline — the
socket timeout bounds every chunk read, so a stalled peer becomes a
typed timeout the caller's death path classifies, not a hang. The
deadline in scope silences HVD014 (and HVD011)."""


def pull_bounded(conn, total, timeout):
    conn.settimeout(timeout)
    buf = b""
    while len(buf) < total:
        chunk = conn.recv(65536)
        if not chunk:
            raise EOFError("peer closed mid-transfer")
        buf += chunk
    return buf
