"""POSITIVE: filesystem writes issued directly from a SIGTERM handler.
The interrupted code may be mid-write to the same checkpoint file (or
holding the allocator/IO locks the write needs) — the handler must only
set a flag; the loop snapshots at its next boundary."""

import json
import signal


class PanicCheckpointer:
    def __init__(self, path, state):
        self.path = path
        self.state = state
        signal.signal(signal.SIGTERM, self._panic_save)

    def _panic_save(self, signum, frame):
        with open(self.path, "w") as f:  # EXPECT: HVD007
            f.write(json.dumps(self.state))  # EXPECT: HVD007
