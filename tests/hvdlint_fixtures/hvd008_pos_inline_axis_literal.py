"""HVD008 positive: a module hand-rolls its sharding against the
data-parallel axis by string convention — the exact per-module coupling
ROADMAP item 2's LogicalMesh refactor must unwind. Every flagged line
is one rewrite site on that refactor's work list."""

from jax import lax
from jax.sharding import PartitionSpec as P


def all_mean(x):
    return lax.psum(x, "hvd") / lax.axis_size("hvd")  # EXPECT: HVD008  # EXPECT: HVD008


def batch_spec():
    return P("hvd")  # EXPECT: HVD008
