"""NEGATIVE: the bucketed fusion lane itself, and loops whose collective
input is the whole (loop-invariant) tensor set — ``grouped_allreduce``
packs the leaves into flat fusion-threshold buckets, so iterating steps
around it is the correct shape and must stay silent.
"""

import horovod_tpu.jax as hvd


def average_gradients(grads):
    return hvd.grouped_allreduce(grads, average=True)


def train(run_step, state, batches):
    for batch in batches:
        state, metrics = run_step(state, batch)
        metrics = hvd.grouped_allreduce(list(metrics.values()))
    return state
