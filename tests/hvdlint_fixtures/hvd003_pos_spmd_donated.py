"""POSITIVE: same bug through the SPMD wrapper (spmd_fn forwards
donate_argnums to jax.jit) and with the scalar donate_argnums spelling;
two donated positions, both later reads flagged.
"""

from horovod_tpu.parallel.spmd import spmd_fn


def run(step, state, opt_state, batch):
    f = spmd_fn(step, donate_argnums=(0, 1))
    out = f(state, opt_state, batch)
    stale = state  # EXPECT: HVD003
    also_stale = opt_state  # EXPECT: HVD003
    return out, stale, also_stale
