"""HVD011 negative: ordinary file reads and timeout-scoped sockets.

``f.read()`` on a local file cannot hang on a dead peer (no peer), and
a socket read inside a function that threads a ``timeout`` argument
has the deadline discipline in scope.
"""


def load_manifest(path):
    with open(path) as f:
        return f.read()


def fetch(sock, nbytes, timeout=5.0):
    sock.settimeout(timeout)
    return sock.recv(nbytes)
