"""HVD008 negative, post-LogicalMesh shape: sharding expressed in
LOGICAL axis names resolved through the rules table — no physical
hvd/ici/dcn spelling anywhere, so the call site survives any mesh
relayout. This is the idiom the hard-fail gate enforces."""

from horovod_tpu.parallel.logical import DATA_AXIS, LogicalMesh, module_axis


def batch_spec(lm: LogicalMesh):
    return lm.spec("batch", "embed")


def data_axis():
    return module_axis("data")


def legacy_axis_constant():
    return DATA_AXIS
