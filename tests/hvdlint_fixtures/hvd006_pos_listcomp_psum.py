"""POSITIVE: per-leaf ``lax.psum`` from a comprehension inside an SPMD
step — same per-tensor collective cost as the loop form, one latency +
dispatch per gradient leaf; the fused bucket lane
(``fused_reduce``/``DistributedOptimizer``) exists for exactly this.
"""

import jax
from jax import lax


def reduce_tree(grads, axis):
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    reduced = [lax.psum(leaf, axis) for leaf in leaves]  # EXPECT: HVD006
    return jax.tree_util.tree_unflatten(treedef, reduced)


def mean_tree(grads, axis):
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    reduced = {i: lax.pmean(g, axis) for i, g in enumerate(leaves)}  # EXPECT: HVD006
    return treedef, reduced
