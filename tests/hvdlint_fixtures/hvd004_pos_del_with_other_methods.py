"""POSITIVE: having other methods does not help — none of them is a
deterministic release path (release/close/shutdown/__exit__/...); the
only cleanup is still the finalizer.
"""


class TimelineWriter:
    def __init__(self, path):
        self.f = open(path, "w")

    def write_event(self, event):
        self.f.write(event)

    def flush(self):
        self.f.flush()

    def __del__(self):  # EXPECT: HVD004
        self.f.close()
