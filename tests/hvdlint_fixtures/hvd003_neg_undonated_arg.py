"""NEGATIVE: reuse of a NON-donated argument — only position 0 is
donated; ``batch`` (position 1) survives the call and may be read
freely.
"""

import jax


def train(step, state, batch):
    f = jax.jit(step, donate_argnums=(0,))
    new_state = f(state, batch)
    stats = batch.mean()
    return new_state, stats
