"""NEGATIVE: a class with deterministic cleanup and NO finalizer at all
— nothing for the rule to say (whether to add a backstop __del__ is a
judgement call, not a lint)."""


class LogSink:
    def __init__(self, path):
        self.f = open(path, "a")

    def close(self):
        self.f.close()
