"""NEGATIVE: ordinary checkpoint writes in plain (non-handler) code —
the same open/write/replace calls HVD007 flags inside handlers are the
CORRECT atomic-commit idiom at a step boundary. Only functions actually
registered via signal.signal() are handler context; this module
registers none of these."""

import json
import os
import signal


def write_manifest(path, manifest):
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(manifest))
    os.replace(tmp, path)


def boundary_epilogue(handler_flag, path, manifest):
    # The loop (not the handler) reacts to the deferred flag.
    if handler_flag.triggered:
        write_manifest(path, manifest)


def install(handler_flag):
    signal.signal(signal.SIGTERM, handler_flag.on_signal)
