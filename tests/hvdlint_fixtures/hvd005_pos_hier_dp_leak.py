"""HISTORICAL POSITIVE (ADVICE round-5 #2): the ``_dryrun_hier_dp``
leak, minimized. ``hvd.shutdown()`` sat in the try body after the lane's
assertions; when an assertion failed, the finally restored the env vars
but hvd stayed initialized with the hierarchical mesh, muddying every
subsequent lane's failure mode. The shutdown belonged in the finally
(guarded by an is-initialized check) — where the repo moved it in PR 1.
"""

import os

import horovod_tpu.jax as hvd


def dryrun_hier_dp(run_lane, check):
    saved = dict(os.environ)
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    try:
        hvd.init()
        result = run_lane()
        assert check(result)
        hvd.shutdown()  # EXPECT: HVD005
    finally:
        os.environ.clear()
        os.environ.update(saved)
