"""NEGATIVE: wall-clock arithmetic that is not a device-timing bracket —
launcher deadlines and pure-host work. Deadline sums never register a
timer variable, and host-only regions have no dispatch call; both must
stay silent.
"""

import time


def wait_with_deadline(proc, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return True
        time.sleep(0.1)
    return False


def host_only_timing(records):
    t0 = time.perf_counter()
    total = sum(len(r) for r in records)
    parsed = [r.strip() for r in records]
    return total, len(parsed), time.perf_counter() - t0
