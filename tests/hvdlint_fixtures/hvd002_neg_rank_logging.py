"""NEGATIVE: rank-conditional side effects with no collective inside the
branch (rank-0 logging/saving) — the canonical correct use of rank().
The collective runs unconditionally before the branch.
"""

import horovod_tpu.jax as hvd


def train_log(metrics, path):
    averaged = hvd.allreduce(metrics, average=True)
    if hvd.rank() == 0:
        with open(path, "a") as f:
            f.write(f"{averaged}\n")
    return averaged
