"""HVD010 positive: a supervisor that relaunches a dead worker in a
bare ``while True:`` — no sleep between attempts, no attempt counter.
A worker that crash-loops (bad binary, poisoned checkpoint) re-crashes
instantly, so this loop spins at full speed forever."""


def supervise_forever(cmd):
    while True:
        result = relaunch_worker(cmd)  # EXPECT: HVD010
        if result.code == 0:
            return 0


def relaunch_worker(cmd):
    raise NotImplementedError
