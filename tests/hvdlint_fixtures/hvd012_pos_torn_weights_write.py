"""HVD012 positive: raw binary weights blob written in place.

The serving-fleet shape this rule encodes: a params blob streamed to
its FINAL path with open(..., "wb") — a worker killed mid-write (the
whole reason the fleet transport exists) leaves a truncated blob, and
the next incarnation loads a prefix of the model as if it were the
model. No rename commit and no digest check anywhere in scope.
"""


def persist_weights(weights_path, blob):
    with open(weights_path, "wb") as f:  # EXPECT: HVD012
        f.write(blob)


def restore_weights(weights_path):
    with open(weights_path, "rb") as f:
        return f.read()
