"""HVD008 negative: prose that merely MENTIONS an axis name — log
lines, error messages, docstrings — is not an axis-name use site; only
exact-match string constants fire."""


def explain(axis):
    if axis is None:
        raise ValueError(
            "no active mesh axis; run inside spmd_run (the default "
            "mesh names its data-parallel axis 'hvd')")
    return f"reducing over {axis} (an hvd-style 1-D mesh)"
