"""NEGATIVE: the supported defer-to-step-boundary pattern
(horovod_tpu/elastic/signals.py): the handler ONLY sets a flag —
async-signal-safe by construction — and the training loop performs the
drain + snapshot at its next step boundary. hvdlint must stay silent."""

import signal


class DeferredPreemption:
    def __init__(self):
        self.triggered = False
        self.signum = None
        signal.signal(signal.SIGTERM, self._on_signal)

    def _on_signal(self, signum, frame):
        self.triggered = True
        self.signum = signum

    def check(self):
        return self.triggered
