"""HVD012 positive: checkpoint written straight to its final path.

A crash (or SIGKILL) halfway through np.savez leaves a torn file at
exactly the path the next restore opens — numpy parses the truncated
container "successfully" for the leaves that landed, and the run
resumes with silently wrong weights. No temp+rename commit, no digest.
"""

import numpy as np


def save_checkpoint(params, path):
    np.savez(path, **params)  # EXPECT: HVD012


def load_checkpoint(path):
    with np.load(path) as z:
        return dict(z)
