"""NEGATIVE: a loop-invariant collective inside a step/epoch loop — the
reduced tensor does not vary with the loop variable (one metric scalar
per step, the reference's metric-average pattern), so there is no
per-tensor fan-out for the fusion lane to amortize.
"""

import horovod_tpu.jax as hvd


def train(run_step, state, loss, num_steps):
    for _ in range(num_steps):
        state, loss = run_step(state)
        avg = hvd.allreduce(loss, average=True, name="train.loss")
    return state, avg


def epoch_summary(epochs, accuracy):
    history = []
    for epoch in range(epochs):
        history.append(hvd.allreduce(accuracy, name="val.accuracy"))
    return history
