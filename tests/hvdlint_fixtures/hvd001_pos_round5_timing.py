"""HISTORICAL POSITIVE (round 5, PERF.md "ROUND-5 CORRECTION"): the
pre-round-5 benchmark timed async XLA dispatch, not the device — on the
tunneled backend nothing in the timed region forced completion, and the
ResNet lane read ~22x the chip's true rate. Minimized from the
pre-correction bench.py window loop / chip probe.

Fixture corpus only — never executed, only parsed by hvdlint.
"""

import time


def timed_window(run_step, state, batch, iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        state, _ = run_step(state, batch)
    return iters / (time.perf_counter() - t0)  # EXPECT: HVD001
