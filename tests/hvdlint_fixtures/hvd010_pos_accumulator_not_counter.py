"""HVD010 positive: an ACCUMULATOR is not an attempt counter. The
``data += chunk`` concatenation (and the non-literal ``total =
total + n`` byte tally) bound nothing — the reconnect still retries
at full speed forever, so the rule must fire through them."""


def read_forever(sock):
    data = b""
    total = 0
    while True:  # EXPECT: HVD014 (chunk loop, no deadline/CRC either)
        chunk = sock.recv(4096)  # EXPECT: HVD011 (unbounded too)
        data += chunk
        n = len(chunk)
        total = total + n
        if not chunk:
            reconnect(sock)  # EXPECT: HVD010


def reconnect(sock):
    raise NotImplementedError
