"""POSITIVE: an atexit teardown callback hard-exiting with an ad-hoc
code. The launcher's per-worker exit classification sees 3 -> "crashed"
and the elastic supervisor burns budget on a deliberate teardown; the
taxonomy constants (EXIT_CLEAN/EXIT_USAGE/EXIT_PREEMPTED/EXIT_RESIZED)
are the only codes the supervisor understands."""

import atexit
import os


def _teardown():
    os._exit(3)  # EXPECT: HVD009


atexit.register(_teardown)
