"""HVD011 positive: a length-prefixed frame read that blocks forever.

The reader recv()s with no socket timeout and no deadline anywhere in
scope: a peer killed mid-write (the exact crash the fleet transport
exists to survive) leaves this thread blocked in the kernel forever —
no exception, no heartbeat, nothing for a watchdog to classify.
"""

import struct


def read_frame(sock):
    header = sock.recv(8)  # EXPECT: HVD011
    (length,) = struct.unpack("<Q", header)
    payload = b""
    while len(payload) < length:  # EXPECT: HVD014 (chunk loop, no CRC)
        payload += sock.recv(length - len(payload))  # EXPECT: HVD011
    return payload
