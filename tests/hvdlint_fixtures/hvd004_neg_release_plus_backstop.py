"""NEGATIVE: the repaired Handle shape — explicit ``release()`` (and
context-manager exit) as the deterministic path, ``__del__`` kept only
as a GC backstop. This is what horovod_tpu/jax/mpi_ops.py ships.
"""


class OpHandle:
    def __init__(self, name, registry):
        self.name = name
        self.registry = registry
        registry.add(name)

    def release(self):
        self.registry.discard(self.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __del__(self):
        self.release()
