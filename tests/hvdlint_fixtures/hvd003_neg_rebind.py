"""NEGATIVE: the supported donation pattern — the variable is rebound
from the call result (``state = f(state, batch)``), so every later read
sees the new buffer. This is how bench.py and the window loop consume
donated train states; hvdlint must stay silent.
"""

import jax


def train_loop(step, state, batches):
    f = jax.jit(step, donate_argnums=(0,))
    for batch in batches:
        state = f(state, batch)
    return state.params.sum()
