"""HVD013 positive: request teardown frees pages straight through the
allocator.

Under prefix caching the pages this request maps may be shared: hit
pages live in other requests' tables too, and the radix index holds
its own +1 on every indexed page. ``free()`` is the strict
single-holder path — on a shared page it raises mid-teardown (and a
weaker allocator would hand the page to a new request while the old
holders still read it). Teardown must ``release()``.
"""


def teardown_request(cache, req):
    req.page_table[:] = 0
    cache.allocator.free(req.pages)  # EXPECT: HVD013
    req.pages.clear()
