"""POSITIVE: the same deadlock spelled as a rank-guarded early return —
non-zero ranks leave the function before the collective below, so rank 0
waits forever in the allgather negotiation.
"""

import horovod_tpu.jax as hvd


def checkpoint_metrics(metrics):
    if hvd.rank() != 0:
        return None  # EXPECT: HVD002
    gathered = hvd.allgather(metrics)
    return gathered
