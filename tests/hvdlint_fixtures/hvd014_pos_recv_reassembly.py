"""HVD014 positive: KV-page reassembly loop pulling chunks off a
connection with neither discipline in scope. The unbounded recv also
fires HVD011 (same hang, per-call shape) — both anchor lines are
marked."""


def pull_pages(conn, total):
    buf = b""
    while len(buf) < total:  # EXPECT: HVD014
        chunk = conn.recv(65536)  # EXPECT: HVD011
        if not chunk:
            raise EOFError("peer closed mid-transfer")
        buf += chunk
    return buf
