"""NEGATIVE: the repaired shape — cleanup in the finally, guarded by an
is-active check, alongside the env restore (what __graft_entry__'s
_dryrun_hier_dp does since PR 1)."""

import os

import horovod_tpu.jax as hvd


def dryrun_hier_dp(run_lane, check):
    saved = dict(os.environ)
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    try:
        hvd.init()
        result = run_lane()
        assert check(result)
    finally:
        if hvd.is_initialized():
            hvd.shutdown()
        os.environ.clear()
        os.environ.update(saved)
