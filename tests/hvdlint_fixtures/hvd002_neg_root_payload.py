"""NEGATIVE: the legitimate root-prepares-payload pattern — only the
branch body is rank-conditional (filling the buffer); the collective
itself is OUTSIDE the branch and every rank reaches it. This is how
broadcast_object works on both binding lanes; hvdlint must stay silent.
"""

import numpy as np

import horovod_tpu.jax as hvd


def broadcast_object_bytes(payload, root_rank, nbytes):
    buf = np.zeros(nbytes, dtype=np.uint8)
    if hvd.rank() == root_rank:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    return hvd.broadcast(buf, root_rank)
