"""NEGATIVE: non-taxonomy exits OUTSIDE handler context are ordinary
CLI behavior (argparse exits 2 itself; mains exit whatever they like) —
the rule only polices functions whose exit code reaches the supervisor
from a registered signal handler or atexit callback."""

import signal
import sys


class FlagOnly:
    def __init__(self):
        self.triggered = False
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self.triggered = True


def main():
    if not FlagOnly():
        sys.exit(13)   # not a handler: fine
    return 0
