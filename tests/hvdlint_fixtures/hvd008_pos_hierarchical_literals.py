"""HVD008 positive: the hierarchical ladder's axis names spelled inline
at a use site — "ici"/"dcn" are mesh-factory vocabulary
(parallel/mesh.py owns them; everywhere else is convention coupling)."""


def ladder_axes(flat):
    inner = {"ici": 8}  # EXPECT: HVD008
    outer = {"dcn": flat // 8}  # EXPECT: HVD008
    return {**outer, **inner}
