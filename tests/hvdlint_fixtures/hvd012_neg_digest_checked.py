"""HVD012 negative: digest-disciplined artifact write (the
serve/params_wire.py assembler shape): the writer records the blob's
sha256 beside it and the loader verifies before trusting a byte — a
torn or corrupted artifact is a typed rejection, never a load, so the
in-place write is safe to observe.
"""

import hashlib
import json


def save_params_blob(params_path, blob):
    digest = hashlib.sha256(blob).hexdigest()
    with open(params_path, "wb") as f:
        f.write(blob)
    with open(params_path + ".sha256", "w") as f:
        json.dump({"sha256": digest, "bytes": len(blob)}, f)


def load_params_blob(params_path):
    with open(params_path, "rb") as f:
        blob = f.read()
    with open(params_path + ".sha256") as f:
        want = json.load(f)["sha256"]
    if hashlib.sha256(blob).hexdigest() != want:
        raise ValueError("torn or corrupted params artifact")
    return blob
