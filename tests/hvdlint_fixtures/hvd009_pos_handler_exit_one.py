"""POSITIVE: a SIGTERM handler that exits 1 after its (deferred) drain.
The elastic supervisor classifies exit 1 as a CRASH and burns a restart
on what was actually a clean preemption — the exit code IS the recovery
protocol (run.driver.classify_exit); handlers must exit through the
EXIT_* taxonomy constants (75 = preempted here)."""

import signal
import sys


class EagerShutdown:
    def __init__(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self.triggered = True
        sys.exit(1)  # EXPECT: HVD009
