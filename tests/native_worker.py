"""Subprocess worker for the native-core multi-process tests.

The reference ran its test files under ``mpirun -np N`` (SURVEY §4 /
reference test/common.py:25-58); this worker is the rebuild's equivalent:
``test_native_core.py`` spawns N of these and each asserts closed-form
collective results against its (rank, size).
"""

import os
import sys

import numpy as np

from horovod_tpu.native import NativeCore, NativeError


def run(rank: int, size: int, port: int, scenario: str) -> None:

    # Host grouping as the launcher would pass it down (run/__init__.py
    # sets HOROVOD_LOCAL_RANK/LOCAL_SIZE per host); defaults to one group.
    local_size = int(os.environ.get("HOROVOD_LOCAL_SIZE", str(size)))
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", str(rank)))
    core = NativeCore()
    timeout_ms = int(os.environ.get("HVD_TEST_INIT_TIMEOUT_MS", "30000"))

    if scenario == "subcomm":
        return _run_subcomm(core, rank, size, port, timeout_ms)
    if scenario == "subcomm_mismatch":
        return _run_subcomm_mismatch(core, rank, size, port, timeout_ms)

    core.init(rank=rank, size=size, local_rank=local_rank,
              local_size=local_size,
              coord_host="127.0.0.1", coord_port=port,
              timeout_ms=timeout_ms)
    core.set_cycle_time_ms(1.0)
    assert core.rank() == rank and core.size() == size

    if scenario == "collectives":
        # allreduce == elementwise sum over ranks.
        a = np.arange(256, dtype=np.float32) * (rank + 1)
        h = core.allreduce_async_("ar", a)
        core.wait(h)
        core.release(h)
        scale = sum(r + 1 for r in range(size))
        assert np.allclose(a, np.arange(256, dtype=np.float32) * scale)

        # Fusion exercised by volume (reference test_*_fused pattern,
        # test_tensorflow.py:107-139): many small tensors in one cycle.
        arrs, handles = [], []
        for i in range(64):
            x = np.full(5, float(rank + i), dtype=np.float32)
            arrs.append(x)
            handles.append(core.allreduce_async_(f"small.{i}", x))
        for i, h in enumerate(handles):
            core.wait(h)
            core.release(h)
            assert np.allclose(arrs[i], sum(r + i for r in range(size)))

        # Ragged allgatherv: rank r contributes r+1 rows.
        g = np.full((rank + 1, 3), rank, dtype=np.int64)
        h = core.allgather_async("ag", g)
        core.wait(h)
        out = core.take_result(h, np.int64, (3,))
        assert out.shape[0] == sum(r + 1 for r in range(size))
        off = 0
        for r in range(size):
            assert (out[off:off + r + 1] == r).all()
            off += r + 1

        # Broadcast from a non-zero root.
        root = size - 1
        b = np.full(16, rank * 10.0, dtype=np.float64)
        h = core.broadcast_async_("bc", b, root)
        core.wait(h)
        core.release(h)
        assert (b == root * 10.0).all()

        # float16 ring reduction (native half math).
        f16 = np.ones(33, dtype=np.float16) * (rank + 1)
        h = core.allreduce_async_("f16", f16)
        core.wait(h)
        core.release(h)
        assert np.allclose(f16, scale, atol=0.01)

    elif scenario == "errors":
        # Mismatched dtypes must produce the negotiation error on every
        # rank (reference test pattern, test_tensorflow.py:265-333).
        bad = np.zeros(4, dtype=np.float32 if rank == 0 else np.float64)
        try:
            h = core.allreduce_async_("bad_dtype", bad)
            core.wait(h)
            raise SystemExit("mismatched dtype was accepted")
        except NativeError as e:
            assert "Mismatched data types" in str(e), str(e)

        bad2 = np.zeros(4 + rank, dtype=np.float32)
        try:
            h = core.allreduce_async_("bad_shape", bad2)
            core.wait(h)
            raise SystemExit("mismatched shape was accepted")
        except NativeError as e:
            assert "Mismatched tensor shapes" in str(e), str(e)

        bad3 = np.zeros(4, dtype=np.float32)
        try:
            h = core.broadcast_async_("bad_root", bad3, rank % 2)
            core.wait(h)
            raise SystemExit("mismatched broadcast roots were accepted")
        except NativeError as e:
            assert "root rank" in str(e), str(e)

        # Recovery: the job keeps working after negotiation errors.
        ok = np.ones(8, dtype=np.float32)
        h = core.allreduce_async_("after_error", ok)
        core.wait(h)
        core.release(h)
        assert np.allclose(ok, float(size))

    elif scenario == "autotune_sync":
        # Rank-0's autotuned {cycle time, fusion threshold} must propagate
        # to every rank via the broadcast ResponseList (reference
        # SyncParams, parameter_manager.h:95-96,232). Start each rank with
        # deliberately different knobs; after the tuner converges all
        # ranks must report identical values.
        import time

        core.set_cycle_time_ms(0.2 + 0.1 * rank)
        core.set_fusion_threshold((rank + 1) * (1 << 20))
        core.enable_autotune("")
        deadline = time.time() + 90
        step = 0
        converged = False
        while time.time() < deadline and not converged:
            for _ in range(25):
                a = np.ones(2048, dtype=np.float32)
                h = core.allreduce_async_(f"ats.{step}", a)
                core.wait(h)
                core.release(h)
                step += 1
            snap = np.array(
                [[core.cycle_time_ms(), float(core.fusion_threshold())]],
                dtype=np.float64)
            h = core.allgather_async(f"params.{step}", snap)
            core.wait(h)
            out = core.take_result(h, np.float64, (2,))
            # Every rank started with distinct hand-set knobs, and only
            # rank 0 ever tunes, so all rows being equal is only possible
            # if the sync overwrote the workers' values with rank-0's.
            converged = bool((out == out[0]).all())
        assert converged, "autotuned parameters never converged across ranks"

    elif scenario == "hier":
        # Two-level collectives (reference hierarchical allreduce
        # operations.cc:1284-1436 / allgather :929-1032, rebuilt as
        # local-ring + cross-ring ladders in csrc/collectives.cc). The
        # launcher env sets HOROVOD_HIERARCHICAL_* knobs; this scenario
        # asserts both that the hierarchical path is ACTIVE (or correctly
        # degraded for untileable topologies) and that results match the
        # flat closed forms exactly.
        inner = int(os.environ.get("HOROVOD_HIERARCHICAL_INNER_SIZE", "0"))
        if inner <= 0:  # same fallback semantics as coordinator.cc
            inner = local_size
        tileable = 1 < inner < size and size % inner == 0
        want = 3 if tileable else 0  # allreduce | allgather bits
        # Mismatched-knob tests override the expectation: the coordinator
        # unifies the per-rank votes, so what is ACTIVE can differ from
        # what THIS rank's env requested.
        want = int(os.environ.get("HVD_TEST_WANT_HIER", want))
        assert core.hierarchical_active() == want, (
            core.hierarchical_active(), want)

        # Single large allreduce (count not divisible by inner: exercises
        # the ragged stripe bounds).
        a = np.arange(1003, dtype=np.float64) * (rank + 1)
        h = core.allreduce_async_("h_ar", a)
        core.wait(h)
        core.release(h)
        scale = sum(r + 1 for r in range(size))
        assert np.allclose(a, np.arange(1003, dtype=np.float64) * scale)

        # Fused volume (many small tensors through the fusion buffer, all
        # riding the hierarchical ladder in one pass).
        arrs, handles = [], []
        for i in range(48):
            x = np.full(7, float(rank + i), dtype=np.float32)
            arrs.append(x)
            handles.append(core.allreduce_async_(f"h_small.{i}", x))
        for i, h in enumerate(handles):
            core.wait(h)
            core.release(h)
            assert np.allclose(arrs[i], sum(r + i for r in range(size)))

        # float16 through the two-level ladder (native half math).
        f16 = np.ones(65, dtype=np.float16) * (rank + 1)
        h = core.allreduce_async_("h_f16", f16)
        core.wait(h)
        core.release(h)
        assert np.allclose(f16, scale, atol=0.01)

        # Ragged hierarchical allgatherv: rank r contributes r+1 rows.
        g = np.full((rank + 1, 3), rank, dtype=np.int64)
        h = core.allgather_async("h_ag", g)
        core.wait(h)
        out = core.take_result(h, np.int64, (3,))
        assert out.shape[0] == sum(r + 1 for r in range(size))
        off = 0
        for r in range(size):
            assert (out[off:off + r + 1] == r).all()
            off += r + 1

        # Broadcast still rides the star path untouched.
        b = np.full(9, rank * 2.0, dtype=np.float32)
        h = core.broadcast_async_("h_bc", b, 0)
        core.wait(h)
        core.release(h)
        assert (b == 0.0).all()

        # Multi-MB payload: stripes far beyond kernel socket buffers, so
        # the full-duplex DuplexTransfer path on BOTH sub-rings is what
        # keeps this from deadlocking (same rationale as the flat ring's
        # SendRecv, transport.cc).
        big = np.arange(2_000_003, dtype=np.float32) * (rank + 1)
        h = core.allreduce_async_("h_big", big)
        core.wait(h)
        core.release(h)
        assert np.allclose(
            big, np.arange(2_000_003, dtype=np.float32) * scale), (
            "big mismatch")

    elif scenario == "stall":
        # Rank 1 holds back its request so rank 0's stall checker
        # (coordinator.cc CheckForStalled, parity with reference
        # operations.cc:1625-1672) must warn, then completes the
        # collective so the job still finishes cleanly. The test launcher
        # sets HOROVOD_STALL_WARNING_TIME low and asserts the warning text
        # on rank 0's stderr.
        import time

        if rank == 1:
            time.sleep(3.0)
        a = np.ones(8, dtype=np.float32)
        h = core.allreduce_async_("stalled_t", a)
        core.wait(h)
        core.release(h)
        assert np.allclose(a, float(size))

    else:
        raise SystemExit(f"unknown scenario {scenario}")

    core.shutdown()


def _run_subcomm(core, rank, size, port, timeout_ms):
    """Sub-communicator formation (reference hvd.init(comm=[ranks]),
    common/__init__.py:58-84): even world ranks form one sub-world, odd
    ranks another — with 3 processes that is {0,2} running a collective
    while {1} sits out on its singleton; with 4 it is two concurrent
    independent sub-worlds sharing one launcher rendezvous."""
    comm = [r for r in range(size) if r % 2 == rank % 2]
    sub_rank = comm.index(rank)
    core.init(rank=rank, size=size, coord_host="127.0.0.1", coord_port=port,
              timeout_ms=timeout_ms, comm=comm)
    core.set_cycle_time_ms(1.0)
    assert core.rank() == sub_rank and core.size() == len(comm), (
        core.rank(), core.size(), comm)
    # All members share 127.0.0.1, so local grouping == the sub-world.
    assert core.local_rank() == sub_rank and core.local_size() == len(comm)
    want_hier = int(os.environ.get("HVD_TEST_WANT_HIER", "-1"))
    if want_hier >= 0:
        assert core.hierarchical_active() == want_hier, (
            core.hierarchical_active(), want_hier)

    # Closed-form allreduce within the sub-world only: the sum runs over
    # MEMBER world ranks, proving no cross-sub-world mixing.
    a = np.arange(128, dtype=np.float32) * (rank + 1)
    h = core.allreduce_async_("sub_ar", a)
    core.wait(h)
    core.release(h)
    scale = sum(r + 1 for r in comm)
    assert np.allclose(a, np.arange(128, dtype=np.float32) * scale), scale

    # Broadcast from the sub-world's LAST member (non-zero sub-root when
    # the sub-world has >1 member).
    b = np.full(16, rank * 10.0, dtype=np.float64)
    h = core.broadcast_async_("sub_bc", b, len(comm) - 1)
    core.wait(h)
    core.release(h)
    assert (b == comm[-1] * 10.0).all()

    # Ragged allgatherv: member at sub-rank i contributes i+1 rows.
    g = np.full((sub_rank + 1, 2), rank, dtype=np.int64)
    h = core.allgather_async("sub_ag", g)
    core.wait(h)
    out = core.take_result(h, np.int64, (2,))
    assert out.shape[0] == sum(i + 1 for i in range(len(comm)))
    off = 0
    for i, member in enumerate(comm):
        assert (out[off:off + i + 1] == member).all()
        off += i + 1

    core.shutdown()


def _run_subcomm_mismatch(core, rank, size, port, timeout_ms):
    """An inconsistent split (rank 0 claims {0,1}, everyone else claims
    their singleton) must fail on EVERY rank — collective failure, the
    MPI communicator-creation semantics."""
    comm = [0, 1] if rank == 0 else [rank]
    try:
        core.init(rank=rank, size=size, coord_host="127.0.0.1",
                  coord_port=port, timeout_ms=timeout_ms, comm=comm)
        raise SystemExit("inconsistent comm was accepted")
    except NativeError as e:
        assert "inconsistent sub-communicators" in str(e), str(e)


if __name__ == "__main__":
    run(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
