"""Lifecycle and topology tests.

Ports the reference's rank/size assertions (test/test_tensorflow.py:63-75,
which compared hvd.rank()/size() against mpirun env vars) to the 8-device
virtual mesh.
"""

import numpy as np
import pytest

import horovod_tpu.jax as hvd
from horovod_tpu.common.exceptions import NotInitializedError


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_size_is_device_count(hvd):
    import jax

    assert hvd.size() == jax.device_count() == 8


def test_local_size(hvd):
    import jax

    assert hvd.local_size() == jax.local_device_count()


def test_rank_outside_spmd_is_process_lead(hvd):
    assert int(hvd.rank()) == 0
    assert int(hvd.local_rank()) == 0


def test_rank_inside_spmd_is_chip_index(hvd):
    import jax.numpy as jnp

    ranks = hvd.spmd_run(
        lambda: hvd.allgather(jnp.asarray(hvd.rank(), jnp.int32)[None])
    )
    assert list(np.asarray(ranks)) == list(range(8))


def test_mpi_threads_supported_false(hvd):
    assert hvd.mpi_threads_supported() is False


def test_mesh_axis(hvd):
    assert hvd.mesh().shape["hvd"] == 8


def test_comm_subset_builds_sub_mesh():
    """hvd.init(comm=[ranks]) restricts the job to those chips (reference
    horovod_init(ranks, nranks), operations.cc:1728-1746): size shrinks,
    the mesh holds exactly the subset, collectives span only it. Fresh
    process because init is once-per-process."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    script = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import horovod_tpu.jax as hvd

hvd.init(comm=[0, 2, 4, 6])
assert hvd.size() == 4, hvd.size()
assert [d.id for d in hvd.mesh().devices.ravel()] == [0, 2, 4, 6]
out = hvd.spmd_run(lambda x: hvd.allreduce(x, average=False),
                   jnp.ones((3,), jnp.float32))
assert float(out[0]) == 4.0, out  # spans 4 chips, not 8
try:
    import horovod_tpu.common.basics as b
    b.shutdown()
    hvd.init(comm=[0, 99])
except Exception as e:
    assert "out of range" in str(e), e
    print("COMM_SUBSET_OK")
"""
    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          cwd=str(repo), capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "COMM_SUBSET_OK" in proc.stdout


def test_require_init():
    from horovod_tpu.common.state import GlobalState

    st = GlobalState()
    with pytest.raises(NotInitializedError):
        st.require_init()
