"""Lifecycle and topology tests.

Ports the reference's rank/size assertions (test/test_tensorflow.py:63-75,
which compared hvd.rank()/size() against mpirun env vars) to the 8-device
virtual mesh.
"""

import numpy as np
import pytest

import horovod_tpu.jax as hvd
from horovod_tpu.common.exceptions import NotInitializedError


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_size_is_device_count(hvd):
    import jax

    assert hvd.size() == jax.device_count() == 8


def test_local_size(hvd):
    import jax

    assert hvd.local_size() == jax.local_device_count()


def test_rank_outside_spmd_is_process_lead(hvd):
    assert int(hvd.rank()) == 0
    assert int(hvd.local_rank()) == 0


def test_rank_inside_spmd_is_chip_index(hvd):
    import jax.numpy as jnp

    ranks = hvd.spmd_run(
        lambda: hvd.allgather(jnp.asarray(hvd.rank(), jnp.int32)[None])
    )
    assert list(np.asarray(ranks)) == list(range(8))


def test_mpi_threads_supported_false(hvd):
    assert hvd.mpi_threads_supported() is False


def test_mesh_axis(hvd):
    assert hvd.mesh().shape["hvd"] == 8


def test_require_init():
    from horovod_tpu.common.state import GlobalState

    st = GlobalState()
    with pytest.raises(NotInitializedError):
        st.require_init()
