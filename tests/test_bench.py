"""Smoke tests for the driver's bench entry (`bench.py`).

The driver runs ``python bench.py`` on real hardware at round end; these
tests pin its contract — one JSON line with metric/value/unit/vs_baseline
— on the hermetic 8-device CPU mesh so a refactor can't silently break
the recorded benchmark. Protocol anchor: reference
examples/pytorch_synthetic_benchmark.py:79-110.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run_bench(*args, timeout=600, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["HVD_TPU_FORCE_CPU"] = "1"
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *args],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=timeout)
    assert proc.returncode == 0, (
        f"bench rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1]), proc


def test_default_lane_contract():
    """The exact invocation the driver records (tiny sizes for CI)."""
    out, _ = _run_bench("--batch-size", "2", "--image-size", "64",
                        "--num-warmup-batches", "1",
                        "--num-batches-per-iter", "2", "--num-iters", "2")
    assert out["metric"] == "resnet50_img_per_sec_per_chip"
    assert out["unit"] == "img/sec/chip"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert out["probe_tflops"] > 0


@pytest.mark.parametrize("flags", [
    pytest.param((), id="dense-default"),
    pytest.param(("--fused-ce", "--scan-layers", "--remat"), id="r3-flags"),
])
def test_lm_lane_contract(flags):
    """Long-context lane: tokens/sec with vs_baseline null. Both the
    dense default path (the lane PERF_RUNS.tsv headline numbers come
    from) and the round-3 perf flags (--fused-ce --scan-layers --remat)
    are driven end-to-end so a regression in either path's arg wiring
    or JSON contract is caught."""
    out, proc = _run_bench(
        "--model", "transformer_lm", "--batch-size", "2",
        "--seq-len", "128", "--vocab", "512", "--lm-layers", "2",
        "--lm-dim", "64", "--lm-heads", "4", *flags,
        "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
        "--num-iters", "2")
    assert out["metric"] == "transformer_lm_tokens_per_sec_per_chip"
    assert out["unit"] == "tokens/sec/chip"
    assert out["value"] > 0
    assert out["vs_baseline"] is None
    assert "tokens/sec" in proc.stderr


def test_hung_backend_degrades_to_error_json():
    """A hang (tunnel down, jax.devices() never returns) must not leave a
    stack trace as the official record: the supervisor times the attempt
    out, retries, then emits the contract line with an "error" field and
    rc=0. Simulated by an attempt timeout shorter than the jax import."""
    out, proc = _run_bench(
        "--batch-size", "2", "--image-size", "64",
        extra_env={"HVD_BENCH_ATTEMPTS": "2",
                   "HVD_BENCH_ATTEMPT_TIMEOUT": "1",
                   "HVD_BENCH_BACKOFF": "0.1"})
    assert out["metric"] == "resnet50_img_per_sec_per_chip"
    assert out["unit"] == "img/sec/chip"
    assert out["value"] is None
    assert "timeout" in out["error"]
    assert proc.stderr.count("attempt") >= 2


def test_sigterm_mid_run_still_emits_contract_line():
    """An OUTER deadline (the driver's own timeout) terminating the
    supervisor mid-attempt must still produce the one-JSON-line record
    — the handler kills the measuring child's process group and prints
    the degraded contract before exiting 0."""
    import signal
    import time

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["HVD_TPU_FORCE_CPU"] = "1"
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"),
         "--batch-size", "2", "--image-size", "64"],
        env=env, cwd=str(REPO), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    # Wait for the supervisor to announce attempt 1 (not a fixed sleep:
    # a warm cache could otherwise finish before the signal lands),
    # then give the child a moment to be mid-compile.
    line = ""
    while "attempt 1/" not in line:
        line = proc.stderr.readline()
        assert line, "supervisor exited before announcing an attempt"
    time.sleep(3)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 0, proc.returncode
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["metric"] == "resnet50_img_per_sec_per_chip"
    assert payload["value"] is None
    assert "signal" in payload["error"]


def test_crashing_child_degrades_to_error_json():
    """A deterministic in-child failure (unknown model) is NOT retried —
    the child signals it via a sentinel exit code, the supervisor fails
    fast and still yields the parseable contract line, rc=0."""
    out, proc = _run_bench(
        "--model", "no_such_model",
        extra_env={"HVD_BENCH_ATTEMPTS": "3",
                   "HVD_BENCH_BACKOFF": "0.1"})
    assert out["metric"] == "no_such_model_img_per_sec_per_chip"
    assert out["value"] is None
    assert "deterministic" in out["error"]
    # The record must be self-diagnosing: the child's exception summary
    # rides the error field (round 3's dense seq-4096 rc=3 reached
    # PERF_RUNS.tsv with no reason at all).
    assert "Unknown model" in out["error"]
    # Fail-fast: exactly one attempt despite HVD_BENCH_ATTEMPTS=3.
    assert proc.stderr.count("attempt 1/") == 1
    assert "attempt 2/" not in proc.stderr


def test_lm_flash_attention_lane():
    """--flash-attention swaps the Pallas kernel into the LM lane (the
    flash-vs-dense A/B surface); same contract, interpret mode on CPU.
    The record now also stamps the resolved attention implementation."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--flash-attention",
        "--batch-size", "2", "--seq-len", "128", "--vocab", "256",
        "--lm-layers", "1", "--lm-dim", "64", "--lm-heads", "4",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out["metric"] == "transformer_lm_tokens_per_sec_per_chip"
    assert out["value"] > 0
    assert out["attention"] == "flash"


def test_lm_attention_auto_policy():
    """--attention auto encodes the measured crossover (dense < 4096,
    flash >= 4096 — PERF.md r5 adjudication #2): below the threshold it
    must resolve to dense, and the record says so."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--attention", "auto",
        "--batch-size", "2", "--seq-len", "128", "--vocab", "256",
        "--lm-layers", "1", "--lm-dim", "64", "--lm-heads", "4",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out["attention"] == "dense"
    assert out["flash_grid"] is None
    assert out["value"] > 0


def test_lm_flash_grid_stamp_and_full_grid_ab():
    """Flash records carry the causal-grid accounting (blocks, step
    counts, K/V bytes), and --flash-full-grid pins the full grid — the
    truncated-vs-full A/B pair tools/hw_sweep.py queues. seq 384 tiles
    as a 3x3 block grid, so the packed walk is 6 of 9 steps."""
    common = ("--model", "transformer_lm", "--batch-size", "2",
              "--seq-len", "384", "--vocab", "256", "--lm-layers", "1",
              "--lm-dim", "64", "--lm-heads", "4",
              "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
              "--num-iters", "1")
    out, _ = _run_bench("--attention", "flash", *common)
    g = out["flash_grid"]
    assert out["attention"] == "flash" and g["truncated"]
    assert (g["steps"], g["steps_full"]) == (6, 9)
    assert g["kv_bytes"] * 3 == g["kv_bytes_full"] * 2
    assert g["bwd"] == "scan"  # auto resolves scan below Lk 8192
    out_full, _ = _run_bench("--attention", "flash", "--flash-full-grid",
                             "--flash-bwd", "pallas", *common)
    g_full = out_full["flash_grid"]
    assert not g_full["truncated"]
    assert g_full["steps"] == g_full["steps_full"] == 9
    assert g_full["bwd"] == "pallas"  # the A/B lanes' pinned backward


def test_overlap_and_bucket_stamps_in_record():
    """--overlap stamps the knob AND the fused bucket plan (count / MB /
    oversize singletons — the same accounting tools/scaling_model.py
    consumes) into the JSON record, so the hw_sweep overlap A/B rows
    carry their dispatch-shape evidence; --d-model is the documented
    alias for --lm-dim (the GPT-2-medium lane spelling)."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--overlap", "on",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--d-model", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out["overlap"] == "on"
    b = out["buckets"]
    assert b["count"] >= 1 and b["total_bytes"] > 0
    assert {"total_mb", "oversize_singletons", "largest_bytes"} <= set(b)
    assert out["value"] > 0
    # The static collective audit (tools/hvdverify) rides every record:
    # the step program's reduce traffic must carry at least the bucket
    # plan's bytes (scalar metric psums ride on top), with per-kind
    # counts for the perf_summary column.
    c = out["collectives"]
    assert c["count"] >= b["count"]
    assert c["bytes"] >= b["total_bytes"]
    assert c["by_kind"] and sum(c["by_kind"].values()) == c["count"]


def test_wire_leaves_mirror_fused_reduce_compression():
    """The wire stamp's plan must be built over the SAME leaves
    fused_reduce buckets: cast compressors (bf16/fp16) halve floating
    leaves before planning; none/int8/fp8 plan the raw tree (their
    compress() is identity at bucketing time)."""
    import jax
    import jax.numpy as jnp

    from bench import wire_leaves
    from horovod_tpu.jax.compression import Compression

    leaves = [jax.ShapeDtypeStruct((64,), jnp.float32),
              jax.ShapeDtypeStruct((8,), jnp.int32)]
    for comp in (Compression.none, Compression.int8, Compression.fp8):
        assert wire_leaves(leaves, comp) is leaves
    cast = wire_leaves(leaves, Compression.bf16)
    assert cast[0].dtype == jnp.bfloat16 and cast[0].shape == (64,)
    assert cast[1].dtype == jnp.int32  # non-floating leaves untouched


def test_hierarchical_wire_stamp_in_record():
    """--hierarchical on + --compression int8 stamps the resolved ladder
    knob (mode/inner) and the per-leg wire split (ICI vs DCN operand
    bytes, DCN dtype, compression ratio) into the record — the evidence
    the hw_sweep hier/int8 A/B rows and the scaling-model predictions
    are reconciled against. The int8 error-feedback residuals ride the
    optimizer state (sharded specs), so the timed step is the REAL
    quantized exchange, not a stampede of stamps over a flat run."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--hierarchical", "on",
        "--compression", "int8",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--d-model", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1",
        extra_env={"HOROVOD_HIERARCHICAL_INNER_SIZE": "4"})
    assert out["hierarchical"] == {"mode": "on", "inner": 4}
    w = out["wire"]
    assert w["dtype"] == "int8" and w["ratio"] > 2.5
    assert 0 < w["dcn_bytes"] < w["ici_bytes"]
    assert {"ici_mb", "dcn_mb"} <= set(w)
    assert out["value"] > 0
    # The static audit sees the ladder: scatter + gather traffic, and
    # strictly less reduce payload than a flat psum would carry.
    c = out["collectives"]
    assert c["by_kind"].get("all_to_all") or c["by_kind"].get(
        "all_gather"), c
    # Ladder off (default auto on a single-slice mesh): stamp says so.
    out2, _ = _run_bench(
        "--model", "transformer_lm",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--d-model", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out2["hierarchical"]["inner"] == 0
    assert out2["wire"] is None


def test_snapshot_stamp_in_record():
    """--snapshot-every K measures the elastic host-RAM snapshot cost
    and stamps cadence / ms-per-snapshot / overhead%% into the record
    (ISSUE acceptance: overhead <= 2%% of step time at the default
    cadence of 100). The tiny-LM CPU lane has millisecond steps against
    a sub-millisecond state copy, so the budget holds here too."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--snapshot-every", "100",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--lm-dim", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    s = out["snapshot"]
    assert s["every"] == 100
    assert s["ms_per_snapshot"] > 0
    assert 0 < s["overhead_pct"] <= 2.0
    assert out["value"] > 0
    # Off by default: the historical record shape gains an explicit null.
    out_off, _ = _run_bench(
        "--model", "transformer_lm", "--batch-size", "2",
        "--seq-len", "64", "--vocab", "256", "--lm-layers", "1",
        "--lm-dim", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out_off["snapshot"] is None


def test_mesh_flag_canonicalizes_and_rejects_invalid():
    """--mesh is parsed through the logical-axis vocabulary at argparse
    time: any axis order canonicalizes to the registry's spelling
    ('tp=4,dp=8' and 'dp=8,tp=4' stamp identically), an invalid config
    is a usage error (exit 2, the supervisor's fail-fast class) rather
    than a mid-run crash, and the perf_summary mesh column renders the
    stamp (em-dash for unconfigured/pre-registry records)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mesh_mod", REPO / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    parser = bench.build_parser()
    assert parser.parse_args(["--mesh", "tp=4,dp=8"]).mesh == "dp=8,tp=4"
    assert parser.parse_args([]).mesh is None
    with pytest.raises(SystemExit):
        parser.parse_args(["--mesh", "dp=banana"])

    from tools.perf_summary import mesh_cell

    assert mesh_cell({"mesh": "dp=8,tp=4"}) == "dp=8,tp=4"
    assert mesh_cell({"mesh": None}) == "—"
    assert mesh_cell({}) == "—"


def test_mesh_stamp_in_record():
    """--mesh stamps the canonical config into the JSON record, and a
    record without the flag carries an explicit null — degraded error
    records included, so a mesh-configured lane that dies still says
    what stack it ran under."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--mesh", "tp=2,dp=4",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--lm-dim", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out["mesh"] == "dp=4,tp=2"
    assert out["value"] > 0
    # Unconfigured + degraded: the supervisor's error record carries
    # the explicit null (same attempt-timeout shape as
    # test_hung_backend_degrades_to_error_json, kept to one attempt).
    degraded, _ = _run_bench(
        "--batch-size", "2", "--image-size", "64",
        extra_env={"HVD_BENCH_ATTEMPTS": "1",
                   "HVD_BENCH_ATTEMPT_TIMEOUT": "1",
                   "HVD_BENCH_BACKOFF": "0.1"})
    assert degraded["value"] is None
    assert degraded["mesh"] is None


def test_compile_only_lane_contract():
    """--compile-only (the sweep's *_warm lanes): one first step, metric
    <model>_first_step_secs, vs_baseline null — the warm-cache pass big
    models run before their measured lane."""
    out, _ = _run_bench(
        "--model", "transformer_lm", "--compile-only",
        "--batch-size", "2", "--seq-len", "64", "--vocab", "256",
        "--lm-layers", "1", "--lm-dim", "32", "--lm-heads", "2")
    assert out["metric"] == "transformer_lm_first_step_secs"
    assert out["unit"] == "secs"
    assert out["value"] > 0
    assert out["vs_baseline"] is None


def test_zero_composes_with_lm_lane():
    out, _ = _run_bench(
        "--model", "transformer_lm", "--zero", "--batch-size", "2",
        "--seq-len", "64", "--vocab", "256", "--lm-layers", "1",
        "--lm-dim", "32", "--lm-heads", "2",
        "--num-warmup-batches", "1", "--num-batches-per-iter", "1",
        "--num-iters", "1")
    assert out["value"] > 0
