"""Exactness tests for the fused 1x1-conv + BN-statistics Pallas kernel.

The fused path is a performance schedule, not a different computation:
every test pins it against the unfused composition (XLA conv + separate
statistics reductions) on identical weights — values, statistics, AND
gradients. The reference's analogue is its closed-form collective
assertions (reference test/test_tensorflow.py:77-106); here the closed
form is the unfused graph itself. Runs on CPU via the Pallas interpreter
(the kernel auto-selects interpret mode off-TPU).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.resnet import ConvBN
from horovod_tpu.ops.conv_bn import (
    conv1x1_bn_stats,
    fits_fused,
    matmul_bn_stats,
)


@pytest.fixture(scope="module")
def rng():
    return jax.random.PRNGKey(7)


def _unfused(x, w):
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, axis=0), jnp.sum(yf * yf, axis=0)


class TestKernel:
    def test_matches_unfused_f32(self, rng):
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (256, 96), jnp.float32)
        w = jax.random.normal(k2, (96, 128), jnp.float32)
        y, s1, s2 = matmul_bn_stats(x, w, True)
        yr, s1r, s2r = _unfused(x, w)
        np.testing.assert_allclose(y, yr, rtol=1e-6)
        np.testing.assert_allclose(s1, s1r, rtol=1e-5)
        np.testing.assert_allclose(s2, s2r, rtol=1e-5)

    def test_matches_unfused_bf16(self, rng):
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (512, 64), jnp.bfloat16)
        w = jax.random.normal(k2, (64, 64), jnp.bfloat16)
        y, s1, s2 = matmul_bn_stats(x, w, True)
        yr, s1r, s2r = _unfused(x, w)
        # Stats are accumulated over the SAME rounded bf16 y in both
        # paths; only summation order differs (tile-wise vs flat).
        np.testing.assert_allclose(
            y.astype(np.float32), yr.astype(np.float32), rtol=1e-2)
        np.testing.assert_allclose(s1, s1r, rtol=1e-3, atol=1e-1)
        np.testing.assert_allclose(s2, s2r, rtol=1e-3, atol=1e-1)

    def test_irregular_rows_padding_path(self, rng):
        """M with no aligned divisor exercises the zero-pad branch; the
        padded rows must not pollute the statistics."""
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (100, 32), jnp.float32)
        w = jax.random.normal(k2, (32, 16), jnp.float32)
        y, s1, s2 = matmul_bn_stats(x, w, True)
        yr, s1r, s2r = _unfused(x, w)
        assert y.shape == (100, 16)
        np.testing.assert_allclose(y, yr, rtol=1e-6)
        np.testing.assert_allclose(s1, s1r, rtol=1e-5)
        np.testing.assert_allclose(s2, s2r, rtol=1e-5, atol=1e-4)

    def test_gradients_match_unfused(self, rng):
        """The custom VJP (dy_total = dy + ds1 + 2y*ds2 collapsed into
        the standard matmul gradients) vs autodiff of the unfused graph,
        through a BN-like consumer so all three cotangent paths are
        exercised."""
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (128, 48), jnp.float32)
        w = jax.random.normal(k2, (48, 32), jnp.float32) * 0.1

        def consume(y, s1, s2):
            n = y.shape[0]
            mean = s1 / n
            var = s2 / n - mean * mean
            norm = (y - mean) * lax.rsqrt(var + 1e-5)
            return jnp.sum(norm**2) + 0.3 * jnp.sum(jnp.sin(s1)) \
                + 0.1 * jnp.sum(s2**0.5)

        def fused_loss(x, w):
            return consume(*matmul_bn_stats(x, w, True))

        def unfused_loss(x, w):
            return consume(*_unfused(x, w))

        gx_f, gw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
        gx_r, gw_r = jax.grad(unfused_loss, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx_f, gx_r, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(gw_f, gw_r, rtol=1e-4, atol=1e-5)

    def test_conv1x1_strided_matches_xla_conv(self, rng):
        """Strided 1x1 == matmul over the stride-subsampled input."""
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (2, 8, 8, 24), jnp.float32)
        w = jax.random.normal(k2, (1, 1, 24, 40), jnp.float32)
        y, s1, s2 = conv1x1_bn_stats(x, w, strides=(2, 2), interpret=True)
        yr = lax.conv_general_dilated(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-5)
        yf = yr.reshape(-1, 40)
        np.testing.assert_allclose(s1, jnp.sum(yf, 0), rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(
            s2, jnp.sum(yf * yf, 0), rtol=1e-5, atol=1e-4)

    def test_prologue_matches_unfused_f32(self, rng):
        """Phase-2 kernel: relu(x*a+b) @ w + stats vs the materialized
        composition."""
        from horovod_tpu.ops.conv_bn import matmul_prologue_bn_stats

        k1, k2, k3, k4 = jax.random.split(rng, 4)
        x = jax.random.normal(k1, (256, 64), jnp.float32)
        a = jax.random.normal(k2, (64,), jnp.float32) * 0.5 + 1.0
        b = jax.random.normal(k3, (64,), jnp.float32) * 0.1
        w = jax.random.normal(k4, (64, 32), jnp.float32) * 0.1
        y, s1, s2 = matmul_prologue_bn_stats(x, a, b, w, True)
        h = jnp.maximum(x * a[None] + b[None], 0)
        yr, s1r, s2r = _unfused(h, w)
        np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(s1, s1r, rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(s2, s2r, rtol=1e-5, atol=1e-4)

    def test_prologue_padding_rows_masked(self, rng):
        """Regression (review r3): zero-padded rows pass through the
        affine as relu(b) != 0 for positive shifts — the kernel must
        mask them back to zero or the statistics are silently wrong."""
        from horovod_tpu.ops.conv_bn import matmul_prologue_bn_stats

        k1, k2, k3, k4 = jax.random.split(rng, 4)
        x = jax.random.normal(k1, (100, 32), jnp.float32)  # no divisor
        a = jnp.ones((32,), jnp.float32)
        b = jnp.abs(jax.random.normal(k3, (32,))) + 0.5  # positive shifts
        w = jax.random.normal(k4, (32, 16), jnp.float32) * 0.1
        y, s1, s2 = matmul_prologue_bn_stats(x, a, b, w, True)
        h = jnp.maximum(x * a[None] + b[None], 0)
        yr, s1r, s2r = _unfused(h, w)
        assert y.shape == (100, 16)
        np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(s1, s1r, rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(s2, s2r, rtol=1e-5, atol=1e-4)

    def test_prologue_gradients_exact_f64(self, rng):
        """All four cotangent paths (x through the ReLU mask, the affine
        a/b, and w) vs autodiff of the materialized composition, f64."""
        from horovod_tpu.ops.conv_bn import matmul_prologue_bn_stats

        with jax.enable_x64():
            k1, k2, k3, k4 = jax.random.split(rng, 4)
            x = jax.random.normal(k1, (64, 16), jnp.float64)
            a = jax.random.normal(k2, (16,), jnp.float64) * 0.5 + 1.0
            b = jax.random.normal(k3, (16,), jnp.float64) * 0.1
            w = jax.random.normal(k4, (16, 8), jnp.float64) * 0.1

            def consume(y, s1, s2):
                n = y.shape[0]
                mean = s1 / n
                var = s2 / n - mean * mean
                return jnp.sum(((y - mean) * lax.rsqrt(var + 1e-5)) ** 2)

            def fused(p):
                x, a, b, w = p
                return consume(*matmul_prologue_bn_stats(x, a, b, w, True))

            def ref(p):
                x, a, b, w = p
                h = jnp.maximum(x * a[None] + b[None], 0)
                y = h @ w
                return consume(y, jnp.sum(y, 0), jnp.sum(y * y, 0))

            gf = jax.grad(fused)((x, a, b, w))
            gr = jax.grad(ref)((x, a, b, w))
            jax.tree_util.tree_map(
                lambda u, v: np.testing.assert_allclose(
                    u, v, rtol=1e-9, atol=1e-9),
                gf, gr)

    def test_fits_fused_budget(self):
        assert fits_fused(200704, 256, 64)          # resnet50 stage-1 conv1
        assert fits_fused(3136, 1024, 2048)         # stage-4 projection
        assert not fits_fused(4096, 8192, 8192)     # way past VMEM


def _init_convbn(rng, module, x):
    return module.init(rng, x)


class TestConvBNModule:
    def _paths(self, rng, dtype, kernel=(1, 1), strides=(1, 1), axis=None):
        kw = dict(features=12, kernel_size=kernel, strides=strides,
                  dtype=dtype, axis_name=axis)
        return ConvBN(fuse=False, **kw), ConvBN(fuse=True, **kw)

    def test_fused_equals_unfused_f32(self, rng):
        unfused, fused = self._paths(rng, jnp.float32)
        x = jax.random.normal(rng, (4, 6, 6, 8), jnp.float32)
        variables = _init_convbn(rng, unfused, x)
        out_u, stats_u = unfused.apply(
            variables, x, mutable=["batch_stats"])
        out_f, stats_f = fused.apply(variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(out_f, out_u, rtol=1e-5, atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6),
            stats_f, stats_u)

    def _grad_pair(self, rng, dtype):
        unfused, fused = self._paths(rng, dtype)
        x = jax.random.normal(rng, (4, 6, 6, 8), dtype)
        variables = _init_convbn(rng, unfused, x)

        def loss(params, module):
            out, _ = module.apply(
                {"params": params,
                 "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"])
            return jnp.sum(out.astype(dtype) ** 2)

        g_u = jax.grad(loss)(variables["params"], unfused)
        g_f = jax.grad(loss)(variables["params"], fused)
        return g_f, g_u

    def test_fused_grads_equal_unfused_f64_exact(self, rng):
        """The strong statement: in f64 (stats dtype follows the input)
        the fused VJP and the unfused autodiff are the same math — any
        systematic error in the collapsed cotangent formula would show
        here far above 1e-9."""
        with jax.enable_x64():
            g_f, g_u = self._grad_pair(rng, jnp.float64)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=1e-9, atol=1e-9),
                g_f, g_u)

    def test_fused_grads_close_unfused_f32(self, rng):
        """f32: stats summation order differs between the tile-wise
        kernel and the flat reduction, and BN's scale-invariance makes
        the kernel gradient a near-total cancellation — so f32 agreement
        is inherently loose (the f64 test above pins the math)."""
        g_f, g_u = self._grad_pair(rng, jnp.float32)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-2, atol=5e-4),
            g_f, g_u)

    def test_eval_mode_ignores_fuse_flag(self, rng):
        """Eval uses running statistics — no reduction to fuse; both
        flags must produce the identical plain-conv graph."""
        kw = dict(features=5, kernel_size=(1, 1), dtype=jnp.float32,
                  use_running_average=True)
        x = jax.random.normal(rng, (2, 4, 4, 3), jnp.float32)
        variables = _init_convbn(rng, ConvBN(fuse=False, **kw), x)
        out_u = ConvBN(fuse=False, **kw).apply(variables, x)
        out_f = ConvBN(fuse=True, **kw).apply(variables, x)
        np.testing.assert_allclose(out_f, out_u, rtol=0, atol=0)

    def test_sync_bn_fused_matches_unfused_on_mesh(self, hvd, rng):
        """Cross-replica statistics: fused psum(s1/s2/n) must equal the
        unfused pmean path under shard_map over the 8-device mesh."""
        from jax import shard_map

        unfused, fused = self._paths(
            rng, jnp.float32, axis="hvd")
        x = jax.random.normal(rng, (16, 4, 4, 6), jnp.float32)
        variables = _init_convbn(
            rng, ConvBN(features=12, kernel_size=(1, 1),
                        dtype=jnp.float32), x[:2])
        mesh = hvd.mesh()

        def run(module):
            def f(xs):
                out, stats = module.apply(
                    variables, xs, mutable=["batch_stats"])
                return out, stats
            # check_vma=False is REQUIRED here, not a convenience: the
            # Pallas interpreter's grid loop carries output buffers
            # without vma, so the varying-axes check trips inside
            # pallas_call (the JAX error itself prescribes this
            # workaround). Scoped to this shard_map only.
            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=P("hvd"),
                out_specs=(P("hvd"), P()), check_vma=False))(x)

        out_u, stats_u = run(unfused)
        out_f, stats_f = run(fused)
        np.testing.assert_allclose(out_f, out_u, rtol=1e-5, atol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6),
            stats_f, stats_u)


class TestFusedResNet:
    def test_resnet50_style_step_fused_vs_unfused(self, rng):
        """End-to-end: a tiny bottleneck ResNet (every ConvBN flavor —
        stem, 1x1s, strided 3x3, strided projection) computes one loss +
        gradient with fused_bn on/off from identical params. Run in f64
        so agreement is exact-math tight (see the ConvBN-level tests for
        why f32 agreement is inherently loose)."""
        from horovod_tpu.models.resnet import (
            BottleneckResNetBlock, ResNet)

        def build(fused):
            return ResNet(stage_sizes=[1, 1],
                          block_cls=BottleneckResNetBlock,
                          num_classes=5, num_filters=8,
                          dtype=jnp.float64, fused_bn=fused)

        with jax.enable_x64():
            x = jax.random.normal(rng, (4, 16, 16, 3), jnp.float64)
            labels = jax.random.randint(rng, (4,), 0, 5)
            variables = build(False).init(rng, x)

            def loss_fn(params, model):
                logits, _ = model.apply(
                    {"params": params,
                     "batch_stats": variables["batch_stats"]},
                    x, mutable=["batch_stats"])
                onehot = jax.nn.one_hot(labels, 5)
                return -jnp.mean(
                    jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))

            lu, gu = jax.value_and_grad(loss_fn)(
                variables["params"], build(False))
            lf, gf = jax.value_and_grad(loss_fn)(
                variables["params"], build(True))
            np.testing.assert_allclose(lf, lu, rtol=1e-9)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=1e-7, atol=1e-9),
                gf, gu)

    def test_inception_fused_matches_unfused(self, rng):
        """Inception V3's ConvBN rides the same shared module, so its
        many 1x1s take the phase-1 kernel; forward + mutated statistics
        must match the unfused graph on shared weights (the kernel math
        itself is f64-pinned above)."""
        from horovod_tpu.models.inception import InceptionV3

        x = jax.random.normal(rng, (2, 128, 128, 3), jnp.float32)

        def build(fused):
            return InceptionV3(num_classes=5, dtype=jnp.float32,
                               fused_bn=fused)

        variables = build(False).init(rng, x[:1], train=False)
        out_u, st_u = build(False).apply(
            variables, x, mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(3)})
        out_f, st_f = build(True).apply(
            variables, x, mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(3)})
        # Logits accumulate f32 summation-order noise through ~94 BN
        # layers (the same amplification the ResNet f32 tests document;
        # the math is f64-pinned at the kernel/module level above) —
        # the statistics comparison below is the tight pin.
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_u),
                                   rtol=2e-2, atol=5e-3)
        # Deep layers' statistics inherit the upstream drift too; the
        # tolerance still catches any scale-class bug outright.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-3, atol=1e-4),
            st_f, st_u)

    def test_param_tree_identical_between_modes(self, rng):
        from horovod_tpu.models.resnet import ResNet50

        x = jnp.zeros((1, 32, 32, 3))
        tu = jax.eval_shape(
            functools.partial(
                ResNet50(num_classes=3, fused_bn=False).init, rng), x)
        tf = jax.eval_shape(
            functools.partial(
                ResNet50(num_classes=3, fused_bn=True).init, rng), x)
        assert jax.tree_util.tree_structure(tu) == \
            jax.tree_util.tree_structure(tf)
