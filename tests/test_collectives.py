"""Closed-form collective correctness tests over the 8-chip mesh.

Port of the reference's collective assertions (their mechanism: mpirun-
launched size-parametric tests with closed-form expected values —
allreduce == tensor x size (test/test_tensorflow.py:77-106), allgather
slices per rank (test/test_torch.py:430-504), broadcast == root value
(test/test_torch.py:613-648)) onto the SPMD harness.
"""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hvd


@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_allreduce_sum(hvd, dtype):
    base = np.arange(60, dtype=dtype).reshape(3, 4, 5)

    def fn():
        t = (base * (hvd.rank() + 1).astype(dtype)).astype(dtype)
        return hvd.allreduce(t, average=False)

    out = np.asarray(hvd.spmd_run(fn))
    # sum over r of base*(r+1) = base * sum(1..8) = base * 36
    np.testing.assert_allclose(out, base * 36, rtol=1e-6)


def test_allreduce_average(hvd):
    base = np.ones((4, 4), np.float32)

    def fn():
        t = base * hvd.rank().astype(np.float32)
        return hvd.allreduce(t, average=True)

    out = np.asarray(hvd.spmd_run(fn))
    np.testing.assert_allclose(out, base * np.mean(np.arange(8)), rtol=1e-6)


def test_allreduce_min_max(hvd):
    def fn():
        t = np.ones((2, 2), np.float32) * hvd.rank().astype(np.float32)
        return hvd.allreduce(t, op=hvd.Min), hvd.allreduce(t, op=hvd.Max)

    mn, mx = hvd.spmd_run(fn)
    assert float(np.asarray(mn)[0, 0]) == 0.0
    assert float(np.asarray(mx)[0, 0]) == 7.0


def test_allreduce_fp16_compression(hvd):
    base = np.random.RandomState(0).rand(17, 3).astype(np.float32)

    def fn():
        return hvd.allreduce(
            base, average=True, compression=hvd.Compression.fp16
        )

    out = np.asarray(hvd.spmd_run(fn))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, base, rtol=1e-2)


def test_allreduce_bf16_compression(hvd):
    base = np.random.RandomState(1).rand(8, 8).astype(np.float32)

    def fn():
        return hvd.allreduce(
            base, average=True, compression=hvd.Compression.bf16
        )

    out = np.asarray(hvd.spmd_run(fn))
    np.testing.assert_allclose(out, base, rtol=2e-2)


def test_allgather(hvd):
    # Reference: allgather concatenates along dim 0 in rank order
    # (test/test_torch.py:430-504).
    def fn():
        t = np.ones((2, 3), np.float32) * hvd.rank().astype(np.float32)
        return hvd.allgather(t)

    out = np.asarray(hvd.spmd_run(fn))
    assert out.shape == (16, 3)
    for r in range(8):
        np.testing.assert_allclose(out[2 * r : 2 * r + 2], r)


def test_allgatherv_ragged(hvd):
    # Reference allows rank-dependent first dims (operations.cc:843-925);
    # under static SPMD shapes the contract is pad-to-max + per-rank counts.
    max_rows = 8

    def fn():
        rows = hvd.rank() + 1  # rank r contributes r+1 valid rows
        base = np.ones((max_rows, 2), np.float32)
        t = base * hvd.rank().astype(np.float32)
        gathered, counts = hvd.allgatherv(t, rows, max_rows)
        return gathered, counts

    gathered, counts = hvd.spmd_run(fn)
    gathered, counts = np.asarray(gathered), np.asarray(counts)
    assert gathered.shape == (64, 2)
    assert list(counts) == [r + 1 for r in range(8)]
    for r in range(8):
        block = gathered[r * max_rows : (r + 1) * max_rows]
        np.testing.assert_allclose(block[: counts[r]], r)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(hvd, root):
    # Reference: broadcast == root's value everywhere
    # (test/test_torch.py:613-648).
    def fn():
        t = np.full((3, 3), 10.0, np.float32) * (
            hvd.rank().astype(np.float32) + 1.0
        )
        return hvd.broadcast(t, root_rank=root)

    out = np.asarray(hvd.spmd_run(fn))
    np.testing.assert_allclose(out, 10.0 * (root + 1))


def test_broadcast_bool(hvd):
    def fn():
        t = (hvd.rank() % 2 == 0) & np.array([True, False])
        return hvd.broadcast(t, root_rank=1)

    out = np.asarray(hvd.spmd_run(fn))
    assert out.dtype == np.bool_
    assert list(out) == [False, False]


def test_alltoall(hvd):
    def fn():
        # rank r sends value r to every destination slot.
        t = np.ones((8, 4), np.float32) * hvd.rank().astype(np.float32)
        return hvd.alltoall(t)

    out = np.asarray(hvd.spmd_run(fn, out_specs=P("hvd")))
    # After all-to-all, rank d holds [0,1,...,7] in its 8 slots; gathering
    # across ranks tiles that pattern.
    assert out.shape == (64, 4)
    expected = np.repeat(np.tile(np.arange(8), 8), 4).reshape(64, 4)
    np.testing.assert_allclose(out, expected)


def test_reducescatter(hvd):
    def fn():
        t = np.ones((16, 2), np.float32) * hvd.rank().astype(np.float32)
        return hvd.reducescatter(t, average=False)

    out = np.asarray(hvd.spmd_run(fn, out_specs=P("hvd")))
    # Each rank ends with 2 rows of sum over ranks = 28; gathered -> 16 rows.
    assert out.shape == (16, 2)
    np.testing.assert_allclose(out, 28.0)


def test_grouped_allreduce_fusion(hvd):
    # Reference fused tests enqueue 100 small tensors at once
    # (test/test_tensorflow.py:107-139, test/test_torch.py:180-229).
    rng = np.random.RandomState(42)
    bases = [rng.rand(5, 5).astype(np.float32) for _ in range(100)]

    def fn():
        scaled = [b * (hvd.rank() + 1).astype(np.float32) for b in bases]
        return tuple(hvd.grouped_allreduce(scaled, average=False))

    outs = hvd.spmd_run(fn)
    for b, o in zip(bases, outs):
        np.testing.assert_allclose(np.asarray(o), b * 36, rtol=1e-5)


def test_grouped_allreduce_mixed_dtypes(hvd):
    # Mixed-precision interleaving: fusion must group by dtype (reference
    # look-ahead fusion, operations.cc:2160-2264).
    f32 = np.ones((4,), np.float32)
    i32 = np.ones((4,), np.int32)
    bf = np.ones((4,), np.float32)

    def fn():
        outs = hvd.grouped_allreduce(
            [f32, i32, bf], average=False
        )
        return tuple(outs)

    a, b, c = hvd.spmd_run(fn)
    np.testing.assert_allclose(np.asarray(a), 8.0)
    assert np.asarray(b).dtype == np.int32
    np.testing.assert_allclose(np.asarray(b), 8)
    np.testing.assert_allclose(np.asarray(c), 8.0)


def test_fusion_threshold_buckets(hvd):
    from horovod_tpu.jax.fusion import _plan_buckets

    # 4-byte tensors, threshold 10 bytes -> buckets of 2.
    assert _plan_buckets([4, 4, 4, 4], 10) == [[0, 1], [2, 3]]
    # Oversize tensor gets its own bucket.
    assert _plan_buckets([4, 100, 4], 10) == [[0], [1], [2]]
    assert _plan_buckets([], 10) == []


def test_eager_size_one_semantics(hvd):
    # Outside SPMD, a single-process job behaves like hvd.size()==1 in the
    # reference: collectives are identities.
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x)), x)
    np.testing.assert_allclose(np.asarray(hvd.allgather(x)), x)
    np.testing.assert_allclose(np.asarray(hvd.broadcast(x, 0)), x)


def test_async_handles(hvd):
    x = np.ones((4,), np.float32)
    handle = hvd.allreduce_async(x, name="h1")
    out = hvd.synchronize(handle)
    np.testing.assert_allclose(np.asarray(out), x)
    assert hvd.poll(handle) is True


def test_duplicate_inflight_name_raises(hvd):
    from horovod_tpu.common.exceptions import PreconditionError

    x = np.ones((4,), np.float32)
    h1 = hvd.allreduce_async(x, name="dup")
    with pytest.raises(PreconditionError):
        hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h1)
    # After completion the name is free again.
    h2 = hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h2)


def test_handle_release_frees_name_without_gc(hvd):
    # VERDICT round-5 ask #7: a dropped handle's name must be reusable
    # via explicit release(), with no GC assistance — the handle object
    # stays referenced (so __del__ cannot have run) and the collector is
    # off for the duration.
    import gc

    x = np.ones((4,), np.float32)
    h = hvd.allreduce_async(x, name="rel")
    gc.disable()
    try:
        h.release()
        h.release()  # idempotent
        h2 = hvd.allreduce_async(x, name="rel")
        hvd.synchronize(h2)
    finally:
        gc.enable()
    assert h is not None  # keep the first handle alive past the re-register


def test_handle_context_manager_releases(hvd):
    x = np.ones((4,), np.float32)
    with hvd.allreduce_async(x, name="ctx") as h:
        out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), x)
    # Exited: the name is free even though h is still referenced.
    h2 = hvd.allreduce_async(x, name="ctx")
    h2.release()
    assert h is not None


def test_alltoall_indivisible_raises(hvd):
    with pytest.raises(Exception):
        hvd.spmd_run(
            lambda: hvd.alltoall(np.ones((7, 2), np.float32))
        )


@pytest.mark.parametrize("np_", [2, 4, 8])
def test_eager_alltoall_body_matches_allgather_select(hvd, np_):
    """The eager multi-process alltoall now rides a TRUE pairwise
    exchange (eager.process_alltoall -> lax.all_to_all over a one-
    device-per-process mesh; O(bytes)/rank instead of the old
    O(n*bytes) allgather-then-select). Equivalence pin: the new data
    plane must reproduce the OLD shape's result exactly at np<=8."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu.parallel as par
    from horovod_tpu.jax.eager import _alltoall_on_axis

    per = 3
    # Per-"process" inputs: rank r's row block s carries 100*r + s.
    inputs = [np.concatenate(
        [np.full((per, 2), 100.0 * r + s, np.float32)
         for s in range(np_)]) for r in range(np_)]

    # OLD shape: allgather everyone's tensor, select each source's split
    # destined for this rank (the pre-rewrite fallback, verbatim math).
    def old_shape(me):
        gathered = np.stack(inputs)
        splits = np.split(gathered, np_, axis=1)
        return np.concatenate([splits[me][s] for s in range(np_)], axis=0)

    mesh = par.make_mesh({"proc": np_}, devices=jax.devices()[:np_])
    stacked = jnp.asarray(np.concatenate(inputs))
    out = jax.shard_map(
        lambda t: _alltoall_on_axis(t, "proc", 0, 0),
        mesh=mesh, in_specs=P("proc"), out_specs=P("proc"),
        check_vma=False)(stacked)
    out = np.asarray(out)
    rows = np_ * per
    for me in range(np_):
        np.testing.assert_array_equal(out[me * rows:(me + 1) * rows],
                                      old_shape(me))


@pytest.mark.parametrize("np_", [2, 4, 8])
def test_eager_reducescatter_body_matches_reduce_slice(hvd, np_):
    """Ring reduce-scatter (eager.process_reducescatter) vs the old
    full-reduce-then-slice: each rank's stripe of the cross-rank sum,
    bit-for-bit, at np<=8 (integer-valued inputs make every reduction
    order exact)."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu.parallel as par

    from horovod_tpu.jax.eager import _reducescatter_on_axis

    rng = np.random.RandomState(42 + np_)
    per = 2
    inputs = [np.asarray(rng.randint(-6, 7, (np_ * per, 3)), np.float32)
              for _ in range(np_)]

    # OLD shape: full elementwise sum, keep rank me's dim-0 stripe.
    summed = np.sum(inputs, axis=0)

    mesh = par.make_mesh({"proc": np_}, devices=jax.devices()[:np_])
    stacked = jnp.asarray(np.concatenate(inputs))
    out = np.asarray(jax.shard_map(
        lambda t: _reducescatter_on_axis(t, "proc"),
        mesh=mesh, in_specs=P("proc"), out_specs=P("proc"),
        check_vma=False)(stacked))
    assert out.shape == summed.shape
    for me in range(np_):
        np.testing.assert_array_equal(out[me * per:(me + 1) * per],
                                      summed[me * per:(me + 1) * per])


def test_gradient_of_allreduce(hvd):
    # Reference registered allreduce's gradient as allreduce
    # (tensorflow/mpi_ops.py:94-105); with lax.psum this falls out of the
    # transpose rule. d/dx sum_r psum(x_r * (r+1)) per rank = size * (r+1)
    # summed appropriately — check against a closed form.
    import jax
    import jax.numpy as jnp

    def per_rank(x):
        y = hvd.allreduce(x * (hvd.rank() + 1).astype(jnp.float32), average=False)
        return jnp.sum(y)

    def fn(x):
        g = jax.grad(per_rank)(x)
        return hvd.allgather(g[None])

    x = np.ones((3,), np.float32)
    out = np.asarray(hvd.spmd_run(fn, x))
    # grad at rank r = size * (r+1)?? — psum sums over ranks; each rank's
    # cotangent of sum(psum(...)) is 8 (the psum transpose), times (r+1).
    expected = np.stack(
        [np.full((3,), 8.0 * (r + 1), np.float32) for r in range(8)]
    ).reshape(out.shape)
    np.testing.assert_allclose(out, expected)
