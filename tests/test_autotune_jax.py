"""HOROVOD_AUTOTUNE on the XLA/SPMD lane.

Round-1 gap: the env knob only drove the native CPU core; the jax bucket
size (config.fusion_threshold, consumed by horovod_tpu/jax/fusion.py) was
never tuned against measured step time. Reference scoring semantics:
parameter_manager.h:211-217 (windowed scores, warmup discard, converge to
best).
"""

import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


@pytest.fixture(autouse=True)
def _restore_tuned_config(hvd):
    """Every StepAutotuner constructed here mutates the live config
    (thresholds, the hierarchical bool AND the tri-state knob — the
    tuner's whole job is persistent application); restore all of it so
    tuner tests cannot leak a pinned "on"/"off" into the rest of the
    session (resolve_hierarchical reads the tri-state default)."""
    from horovod_tpu.common.state import global_state

    cfg = global_state().config
    saved = (cfg.fusion_threshold, cfg.hierarchical_allreduce,
             cfg.hierarchical_inner_size, cfg.hierarchical)
    yield
    (cfg.fusion_threshold, cfg.hierarchical_allreduce,
     cfg.hierarchical_inner_size, cfg.hierarchical) = saved

REPO = Path(__file__).resolve().parent.parent


def test_step_autotuner_sweeps_and_converges(hvd, tmp_path):
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax.autotune import StepAutotuner
    from horovod_tpu.jax.fusion import fused_reduce

    st = global_state()
    saved_threshold = st.config.fusion_threshold
    log = tmp_path / "autotune_jax.tsv"
    tuner = StepAutotuner(
        st.config, log_path=str(log), candidates=[0, 64 << 20], window=2
    )
    st.autotuner = tuner
    try:
        def step(x, y):
            a, b = fused_reduce([x, y], average=False)
            return a + 1.0, b + 1.0

        run = hvd.spmd_fn(step, in_specs=(P(), P()), out_specs=(P(), P()))
        x = jnp.ones((64,), jnp.float32)
        y = jnp.ones((32,), jnp.float32)
        for _ in range(40):
            x, y = run(x, y)
            if tuner.converged:
                break
        assert tuner.converged, "tuner never converged"
        # Winner applied to the live config.
        assert st.config.fusion_threshold == tuner.best_threshold
        assert tuner.best_threshold in (0, 64 << 20)
        assert tuner.best_score > 0
        # Correctness preserved across re-traces: both tensors went through
        # +1 per step and a (size-preserving) psum over replicated inputs.
        assert np.isfinite(np.asarray(x)).all()
        # Log records warmups, scored samples, and the winner.
        lines = log.read_text().strip().splitlines()
        kinds = [ln.split("\t")[1] for ln in lines]
        assert "warmup" in kinds
        assert kinds.count("sample") == 2  # one scored window per candidate
        assert kinds[-1] == "converged"
        scores = [float(ln.split("\t")[4]) for ln in lines
                  if ln.split("\t")[1] == "sample"]
        assert all(s > 0 for s in scores)
    finally:
        st.autotuner = None
        st.config.fusion_threshold = saved_threshold


def test_winner_applied_to_dispatch_after_convergence(hvd):
    """Regression: convergence bumps the generation one final time, and the
    dispatch handle must re-jit on that bump — otherwise the LAST swept
    candidate's bucket plan (not the winner's) runs for the rest of the
    job, and the stale ``_compiled`` escape hatch lies about it."""
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax.autotune import StepAutotuner
    from horovod_tpu.jax.fusion import fused_reduce

    st = global_state()
    saved_threshold = st.config.fusion_threshold
    tuner = StepAutotuner(st.config, candidates=[0, 64 << 20], window=1)
    st.autotuner = tuner
    try:
        thresholds_seen = []

        def step(x, y):
            # Record the threshold active at TRACE time: one entry per
            # (re)trace, so the list is the program history.
            thresholds_seen.append(st.config.fusion_threshold)
            a, b = fused_reduce([x, y], average=False)
            return a + 1.0, b + 1.0

        run = hvd.spmd_fn(step, in_specs=(P(), P()), out_specs=(P(), P()))
        handle_before = run._compiled
        x = jnp.ones((64,), jnp.float32)
        y = jnp.ones((32,), jnp.float32)
        for _ in range(20):
            x, y = run(x, y)
            if tuner.converged:
                break
        # One more dispatch AFTER convergence triggers the final re-jit.
        x, y = run(x, y)
        assert tuner.converged
        # The last trace happened under the winning threshold.
        assert thresholds_seen[-1] == tuner.best_threshold
        # And the escape hatch tracks the live handle.
        assert run._compiled is not handle_before
    finally:
        st.autotuner = None
        st.config.fusion_threshold = saved_threshold


def test_native_ei_next_suggests_near_peak(hvd):
    """The ctypes bridge to the native GP/EI picks the candidate nearest
    the observed peak of a smooth score curve."""
    from horovod_tpu import native

    xs = [0.0, 9.0, 4.0]
    ys = [1.0, 2.0, 8.0]
    cands = [1.0, 3.0, 5.0, 7.0]
    i = native.ei_next(xs, ys, cands)
    assert cands[i] in (3.0, 5.0)


def test_ei_strategy_converges_near_optimum_with_fewer_probes(hvd, monkeypatch):
    """EI mode probes <= max_probes of the 9-candidate space (vs 9 for a
    sweep) and still lands on (or next to) the optimum of a smooth
    deterministic score curve."""
    import math

    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax import autotune as at

    st = global_state()
    saved_threshold = st.config.fusion_threshold
    fake_now = [0.0]
    monkeypatch.setattr(at.time, "perf_counter", lambda: fake_now[0])

    def duration(threshold):
        # Smooth valley with minimum (fastest window) at 8 MB.
        x = math.log2(1.0 + threshold / float(1 << 20))
        return 1.0 + (x - math.log2(9.0)) ** 2

    tuner = at.StepAutotuner(st.config, window=1, strategy="ei")
    st.config.fusion_threshold = tuner.candidates[0][0]
    try:
        assert len(tuner.candidates) == 9
        for _ in range(100):
            if tuner.converged:
                break
            if tuner.step_done():
                fake_now[0] += duration(st.config.fusion_threshold)
                tuner.end_window()
        assert tuner.converged
        assert len(tuner.probed) <= tuner.max_probes < len(tuner.candidates)
        # Optimum is 8 MB; accept an immediate log-scale neighbor.
        assert tuner.best_threshold in (4 << 20, 8 << 20, 16 << 20), (
            tuner.best_threshold, tuner.probed)
        assert st.config.fusion_threshold == tuner.best_threshold
    finally:
        st.autotuner = None
        st.config.fusion_threshold = saved_threshold


def test_tuner_flips_hierarchy_by_measured_speed(hvd, monkeypatch):
    """Categorical autotuning (reference parameter_manager.h:149-205
    swept hierarchical modes alongside the numeric pair): with a mesh
    that can ladder (inner=2 over 8 chips), the tuner must converge
    with hierarchical allreduce ON when the ladder's windows are
    measurably faster, and OFF when they are slower — driving the live
    config knob fusion.py consumes at trace time."""
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax import autotune as at

    st = global_state()
    saved = (st.config.fusion_threshold, st.config.hierarchical_allreduce,
             st.config.hierarchical_inner_size, st.config.hierarchical)
    fake_now = [0.0]
    monkeypatch.setattr(at.time, "perf_counter", lambda: fake_now[0])

    def run(hier_faster):
        st.config.hierarchical_inner_size = 2  # 8 chips -> 4x2 ladder
        st.config.fusion_threshold = 8 << 20
        st.config.hierarchical_allreduce = False
        tuner = at.StepAutotuner(st.config, window=1, strategy="sweep")
        assert any(h for _, h in tuner.candidates), (
            "default space must include hierarchical candidates on a "
            "ladderable mesh")
        for _ in range(200):
            if tuner.converged:
                break
            if tuner.step_done():
                base = 1.0 + 0.01 * at.StepAutotuner._xform(
                    st.config.fusion_threshold)
                # The winning category's windows run at half the time.
                fast = (st.config.hierarchical_allreduce == hier_faster)
                fake_now[0] += base * (0.5 if fast else 1.0)
                tuner.end_window()
        assert tuner.converged
        return tuner

    try:
        t_on = run(hier_faster=True)
        assert t_on.best_hierarchical is True
        assert st.config.hierarchical_allreduce is True
        # The tri-state knob is pinned alongside the legacy bool, so a
        # flat candidate cannot ladder through the "auto" default on a
        # DCN-present mesh.
        assert st.config.hierarchical == "on"

        t_off = run(hier_faster=False)
        assert t_off.best_hierarchical is False
        assert st.config.hierarchical_allreduce is False
        assert st.config.hierarchical == "off"
    finally:
        st.autotuner = None
        (st.config.fusion_threshold, st.config.hierarchical_allreduce,
         st.config.hierarchical_inner_size, st.config.hierarchical) = saved


def test_owner_handoff_when_first_handle_goes_idle(hvd):
    """Regression: a warmup/eval handle that dispatches first must not pin
    the tuner forever — after 3 windows of owner inactivity, ownership
    hands off to the active handle and the sweep completes."""
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax.autotune import StepAutotuner
    from horovod_tpu.jax.fusion import fused_reduce

    st = global_state()
    saved_threshold = st.config.fusion_threshold
    tuner = StepAutotuner(st.config, candidates=[0, 64 << 20], window=1)
    st.autotuner = tuner
    try:
        def step(x):
            return fused_reduce([x], average=False)[0] * 0.5

        warmup = hvd.spmd_fn(step, in_specs=P(), out_specs=P())
        x = jnp.ones((16,), jnp.float32)
        warmup(x)  # claims the tuner, then never dispatches again

        train = hvd.spmd_fn(step, in_specs=P(), out_specs=P())
        for _ in range(30):
            x = train(x)
            if tuner.converged:
                break
        assert tuner.converged, "tuner stalled on an idle owner"
        assert st.config.fusion_threshold == tuner.best_threshold
    finally:
        st.autotuner = None
        st.config.fusion_threshold = saved_threshold


def test_tuner_changes_bucket_plan(hvd):
    """The swept knob must actually change the traced program's bucket
    plan: threshold 0 gives one collective per tensor, a large threshold
    packs all same-dtype tensors into one."""
    from horovod_tpu.jax.fusion import _plan_buckets

    sizes = [400, 400, 400]
    assert _plan_buckets(sizes, 0) == [[0], [1], [2]]
    assert _plan_buckets(sizes, 64 << 20) == [[0, 1, 2]]


def test_env_knob_creates_tuner(tmp_path):
    """HOROVOD_AUTOTUNE=1 wires the tuner at hvd.init (round-1 gap:
    state.autotuner stayed None forever)."""
    log = tmp_path / "env_autotune.tsv"
    script = f"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import horovod_tpu.jax as hvd
from horovod_tpu.common.state import global_state
from horovod_tpu.jax.fusion import fused_reduce

hvd.init()
tuner = global_state().autotuner
assert tuner is not None, "HOROVOD_AUTOTUNE did not create a tuner"
tuner.window = 1
tuner.candidates = tuner.candidates[:2]

run = hvd.spmd_fn(lambda x: fused_reduce([x], average=False)[0] * 0.5,
                  in_specs=P(), out_specs=P())
x = jnp.ones((16,), jnp.float32)
for _ in range(10):
    x = run(x)
    if tuner.converged:
        break
assert tuner.converged
hvd.shutdown()
print("ENV_TUNER_OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_AUTOTUNE"] = "1"
    env["HOROVOD_AUTOTUNE_LOG"] = str(log)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          cwd=str(REPO), capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "ENV_TUNER_OK" in proc.stdout
    assert log.exists() and "converged" in log.read_text()


def test_end_window_forces_device_sync_before_clock(hvd, monkeypatch):
    """VERDICT round-5 ask #3 (testable half) / weak #4: the tuner's
    step-time probe must enforce the forced-d2h-sync discipline of
    bench.py's _force_sync — block on the step output AND pull a scalar
    off-device — BEFORE it reads the clock. Proven by ordering: a fake
    output leaf records the monotonically-increasing fake clock at the
    moment it is pulled (astype -> d2h path of devsync.force_device_sync),
    and the window's score must be computed from a strictly LATER clock
    value."""
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax import autotune as at

    st = global_state()
    saved_threshold = st.config.fusion_threshold
    clock = [0.0]

    def tick():
        clock[0] += 1.0
        return clock[0]

    monkeypatch.setattr(at.time, "perf_counter", tick)

    events = []

    class RecordingLeaf:
        """Array-like leaf: force_device_sync selects it via .dtype and
        pulls it via .astype(...) -> jnp.sum -> float."""

        dtype = np.float32

        def astype(self, dt):
            events.append(("d2h_pull", clock[0]))
            return np.zeros((), dt)

    # One candidate == the current setting, so a single scored window
    # converges the tuner.
    tuner = at.StepAutotuner(st.config,
                             candidates=[int(st.config.fusion_threshold)],
                             window=1)
    try:
        # Warmup window (discarded), then the scored window.
        assert tuner.step_done()
        tuner.end_window((RecordingLeaf(),))
        events.clear()
        assert tuner.step_done()
        tuner.end_window((RecordingLeaf(),))
        assert events, "end_window never pulled the output off-device"
        pull_clock = events[0][1]
        assert tuner.converged
        # The score was computed from a clock read AFTER the pull: the
        # final perf_counter value exceeds the clock at d2h time.
        assert clock[0] > pull_clock
        # And the sync happened on BOTH windows' path before any clock
        # read of the scored window (events recorded pre-score).
        assert tuner.best_score > 0
    finally:
        st.autotuner = None
        st.config.fusion_threshold = saved_threshold


def test_force_device_sync_pulls_addressable_shard_on_global_arrays():
    """Multi-host: the probe's d2h pull must come from this process's
    addressable shard — jnp.sum on a non-fully-addressable global
    jax.Array raises, which would crash the tuner (and every timing
    harness) at the first window boundary on multi-host."""
    from horovod_tpu.utils.devsync import force_device_sync

    pulled = []

    class FakeShard:
        data = np.ones((2,), np.float32)

    class FakeGlobalArray:
        dtype = np.float32
        is_fully_addressable = False

        @property
        def addressable_shards(self):
            pulled.append(True)
            return [FakeShard()]

        def astype(self, dt):  # must NOT be used on the global array
            raise AssertionError(
                "eager consumption of a non-fully-addressable array")

    got = force_device_sync((FakeGlobalArray(),))
    assert pulled, "did not route through addressable_shards"
    assert got == 2.0  # sum of the local shard

    class EmptyShardArray(FakeGlobalArray):
        @property
        def addressable_shards(self):
            return []

    assert force_device_sync((EmptyShardArray(),)) == 0.0
