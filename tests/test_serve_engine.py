"""Continuous-batching engine exactness + lifecycle
(horovod_tpu/serve/engine.py).

The acceptance pin: N requests through the continuous-batching engine
produce BIT-IDENTICAL greedy tokens to N independent ``lm_decode``
calls — across staggered joins, chunked prefill at awkward sizes,
page-pressure evictions (recompute path), and EOS early exit — under
BOTH decode-attention paths (``ServeConfig.attention``): the dense
gather reference AND the fused paged-attention kernel
(horovod_tpu/ops/paged_attention.py, interpret mode on CPU). The
whole exactness matrix is attention-parametrized; the paged path
additionally pins its static traffic accounting (pages streamed per
step = ``ceil((t+1)/page_size)`` per slot).

The same matrix is additionally MESH-parametrized (``mesh=None`` vs
the tp=4 virtual CPU mesh): under ``ServeConfig.mesh`` the step runs
SPMD with head-sharded pages and a vocab-parallel head, and every
greedy pin must hold bit-identically — the geometry here (H=4) divides
tp=4 exactly for that reason. Heavy tp4 combinations are slow-marked
in tests/conftest.py with the fast stand-ins named there."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import parallel_lm as plm
from horovod_tpu.serve import ServeConfig, ServeEngine

V, LMAX, LAYERS, H, DH, FFN = 64, 64, 2, 4, 4, 32

#: The mesh matrix: unsharded reference vs TP over the virtual CPU
#: mesh (tests/conftest.py forces 8 host devices; tp=4 takes the
#: prefix). One spelling, shared by every parametrized class.
MESHES = [None, "dp=1,tp=4"]
MESH_IDS = ["tp1", "tp4"]


@pytest.fixture(scope="module")
def params():
    return plm.init_lm_params(jax.random.PRNGKey(0), V, LMAX, LAYERS, H,
                              DH, FFN)


def _prompt(i, lp):
    key = jax.random.fold_in(jax.random.PRNGKey(100), i)
    return np.asarray(jax.random.randint(key, (lp,), 0, V), np.int32)


def _ref(params, prompt, steps):
    """The decode lane's greedy stream — the engine's ground truth."""
    return list(np.asarray(
        plm.lm_decode(params, jnp.asarray(prompt)[None], steps))[0])


@pytest.mark.parametrize("mesh", MESHES, ids=MESH_IDS)
@pytest.mark.parametrize("attention", ["gather", "paged"])
class TestGreedyExactness:
    def test_single_request_matches_lm_decode(self, params, attention,
                                              mesh):
        prompt = _prompt(0, 7)
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=2, prefill_chunk=4,
            attention=attention, mesh=mesh))
        req = eng.submit(prompt, 9)
        eng.run()
        assert req.state == "finished"
        assert req.output == _ref(params, prompt, 9)

    @pytest.mark.parametrize("chunk", [1, 3, 4, 16])
    def test_chunked_prefill_is_chunk_invariant(self, params, chunk,
                                                attention, mesh):
        """Any prefill chunking (1-token, non-divisible, whole-prompt)
        yields the identical stream — the rectangular-causal chunk
        rows reproduce lm_prefill's rows exactly."""
        prompt = _prompt(1, 11)
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=1,
            prefill_chunk=chunk, attention=attention, mesh=mesh))
        req = eng.submit(prompt, 5)
        eng.run()
        assert req.output == _ref(params, prompt, 5)

    def test_staggered_joins_bit_identical(self, params, attention,
                                           mesh):
        """The acceptance pin: requests join the running batch at
        different steps; every greedy stream must equal its own
        independent lm_decode call."""
        spec = [(5, 6), (9, 4), (3, 12), (13, 3), (7, 1), (4, 8)]
        prompts = [_prompt(10 + i, lp) for i, (lp, _) in enumerate(spec)]
        refs = [_ref(params, p, n)
                for p, (_, n) in zip(prompts, spec)]
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=40, decode_slots=2, prefill_chunk=4,
            attention=attention, mesh=mesh))
        reqs = [eng.submit(prompts[0], spec[0][1]),
                eng.submit(prompts[1], spec[1][1])]
        for _ in range(3):
            eng.step()
        reqs += [eng.submit(prompts[2], spec[2][1]),
                 eng.submit(prompts[3], spec[3][1])]
        for _ in range(2):
            eng.step()
        reqs += [eng.submit(prompts[4], spec[4][1]),
                 eng.submit(prompts[5], spec[5][1])]
        eng.run()
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref

    def test_eviction_recompute_stays_exact(self, params, attention,
                                            mesh):
        """Lazy admission under page pressure: requests get evicted,
        requeued with their generated prefix, re-prefilled — and the
        final streams are still bit-identical to lm_decode."""
        spec = [(9, 10), (11, 8), (10, 9)]
        prompts = [_prompt(30 + i, lp) for i, (lp, _) in enumerate(spec)]
        refs = [_ref(params, p, n) for p, (_, n) in zip(prompts, spec)]
        eng = ServeEngine(params, ServeConfig(
            page_size=4, num_pages=8, decode_slots=2, prefill_chunk=4,
            admission="lazy", attention=attention, mesh=mesh))
        reqs = [eng.submit(p, n) for p, (_, n) in zip(prompts, spec)]
        eng.run(max_steps=500)
        assert sum(r.evictions for r in reqs) > 0, \
            "test must exercise the eviction path"
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref

    def test_max_new_tokens_one_finishes_at_prefill(self, params,
                                                    attention, mesh):
        prompt = _prompt(2, 6)
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=8,
            attention=attention, mesh=mesh))
        req = eng.submit(prompt, 1)
        eng.run()
        assert req.state == "finished"
        assert req.output == _ref(params, prompt, 1)
        assert req.t_first_token is not None


class TestLifecycle:
    def test_eos_stops_early(self, params):
        prompt = _prompt(3, 6)
        full = _ref(params, prompt, 8)
        eos = full[2]   # declare a mid-stream greedy token the EOS
        stop = full.index(eos) + 1           # first occurrence wins
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=8))
        req = eng.submit(prompt, 8, eos_token=eos)
        eng.run()
        assert req.state == "finished"
        assert req.output == full[:stop]     # EOS token included

    def test_hard_reject_when_never_fits(self, params):
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=4, decode_slots=1, prefill_chunk=4))
        req = eng.submit(np.arange(40, dtype=np.int32) % V, 30)
        assert req.state == "rejected"
        assert not eng.step()

    def test_bounded_queue_rejects_overflow(self, params):
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=1, prefill_chunk=4,
            max_queue=2))
        reqs = [eng.submit(_prompt(4, 5), 2) for _ in range(3)]
        assert [r.state for r in reqs] == ["queued", "queued",
                                          "rejected"]

    @pytest.mark.parametrize("attention", ["gather", "paged"])
    def test_no_donation_pages_stay_valid(self, params, attention):
        """The HVV104-class invariant: the step must not donate the
        page arrays — the PRE-step pages object stays readable after
        the step ran (a donated buffer would raise on use). The paged
        kernel is additionally READ-ONLY over pages (the new-row
        insert stays the scatter outside it), so the invariant is
        identical in both modes (hvdverify: serve.step +
        serve.step_paged under forbid_donation)."""
        prompt = _prompt(5, 6)
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=4,
            attention=attention))
        eng.submit(prompt, 3)
        before = eng.cache.pages
        eng.step()
        # touching the old buffers must not raise (nothing was donated)
        _ = [np.asarray(p["k"]).sum() for p in before]

    def test_late_promoted_request_gets_page_mapped(self, params):
        """Lazy admission: a request promoted by the post-eviction
        promote pass must still get its fresh page slot mapped before
        the compiled step runs — an unmapped (0) table entry would
        write its KV row into the reserved null page and silently
        corrupt the stream. White-box state: slots [A, C] full, B
        ready with its next write position starting an unmapped page,
        pool exhausted; A's page demand evicts C (newest t_admit),
        freeing the slot B is promoted into mid-step."""
        from horovod_tpu.serve.scheduler import Request, RequestState

        cfg = ServeConfig(page_size=4, num_pages=8, decode_slots=2,
                          prefill_chunk=4, admission="lazy")
        eng = ServeEngine(params, cfg)
        alloc = eng.cache.allocator
        pps = eng.cache.pages_per_seq

        def mk(lp, t_admit, n_pages):
            req = Request(prompt=np.arange(lp, dtype=np.int32) % V,
                          max_new_tokens=8)
            req.state = RequestState.DECODE
            req.generated = [1]
            req.output = [1]
            req.t_admit = t_admit
            req.page_table = np.zeros(pps, np.int32)
            req.pages = alloc.alloc(n_pages)
            req.page_table[:n_pages] = req.pages
            return req

        a = mk(4, 0.5, 1)   # next_pos 4 -> needs unmapped page slot 1
        c = mk(7, 0.9, 2)   # newest-admitted: the eviction victim
        b = mk(4, 0.8, 1)   # ready; next_pos 4 -> page slot 1 unmapped
        b.prefill_pos = 4
        eng.slots = [a, c]
        eng.ready = [b]
        hog = alloc.alloc(alloc.available)   # pool exhausted
        assert alloc.available == 0 and hog

        assert eng.step()
        assert c.evictions == 1              # the slot B was given
        assert b in eng.slots
        assert b.page_table[1] != 0, \
            "late-promoted slot reached the compiled step unmapped"
        assert a.page_table[1] != 0

    def test_engine_reports_compiled_once(self, params):
        """Join/leave across steps never recompiles: steps with
        different active-slot patterns reuse the two step programs."""
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=40, decode_slots=2, prefill_chunk=4))
        for i in range(4):
            eng.submit(_prompt(40 + i, 3 + i), 3 + i)
        eng.run()
        if not hasattr(eng._step_mixed, "_cache_size"):
            pytest.skip("no jit cache introspection on this jax")
        mixed = eng._step_mixed._cache_size()
        decode = eng._step_decode._cache_size()
        assert mixed <= 1 and decode <= 1 and mixed + decode >= 1


class TestSampling:
    def test_temperature_topk_deterministic_and_in_range(self, params):
        prompt = _prompt(6, 5)
        cfg = ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                          prefill_chunk=4)
        outs = []
        for _ in range(2):
            eng = ServeEngine(params, cfg)
            req = eng.submit(prompt, 6, temperature=0.8, top_k=8,
                             seed=42)
            eng.run()
            assert req.state == "finished"
            outs.append(req.output)
        assert outs[0] == outs[1]
        assert all(0 <= t < V for t in outs[0])

    @pytest.mark.parametrize("mesh", MESHES, ids=MESH_IDS)
    @pytest.mark.parametrize("attention", ["gather", "paged"])
    def test_greedy_rows_unaffected_by_sampling_neighbors(self, params,
                                                          attention,
                                                          mesh):
        """A greedy request sharing steps with a temperature request
        stays bit-identical to lm_decode (per-slot sampling knobs) —
        the mixed greedy+sampling cell of the attention AND mesh
        matrix (the sampler reads full-vocab logits either way)."""
        pg, ps = _prompt(7, 6), _prompt(8, 6)
        ref = _ref(params, pg, 6)
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=2, prefill_chunk=4,
            attention=attention, mesh=mesh))
        rg = eng.submit(pg, 6)
        rs = eng.submit(ps, 6, temperature=1.2, top_k=4, seed=9)
        eng.run()
        assert rg.output == ref
        assert all(0 <= t < V for t in rs.output)

    def test_sampler_unit(self):
        from horovod_tpu.serve.sampling import sample_tokens

        logits = np.zeros((3, 8), np.float32)
        logits[0, 5] = 3.0          # greedy row
        logits[1] = np.arange(8)    # top-k row
        logits[2, 2] = 9.0
        toks = np.asarray(sample_tokens(
            jnp.asarray(logits),
            np.asarray([0.0, 0.7, 0.0], np.float32),
            np.asarray([0, 2, 0], np.int32),
            np.asarray([1, 1, 1], np.int32),
            np.asarray([0, 0, 0], np.int32)))
        assert toks[0] == 5 and toks[2] == 2
        assert toks[1] in (6, 7)    # top-2 of the ramp


class TestPagedAccounting:
    def test_pages_streamed_per_step_is_ceil_t_plus_one(self, params):
        """The traffic-win pin: every decode step streams exactly
        ``ceil((t+1)/page_size)`` pages per live slot (vs the gather
        path's constant ``Lmax/page_size``), and none of them is the
        reserved null page 0."""
        from horovod_tpu.ops.paged_attention import paged_grid_info

        ps = 4
        eng = ServeEngine(params, ServeConfig(
            page_size=ps, num_pages=32, decode_slots=1,
            prefill_chunk=64, attention="paged"))
        req = eng.submit(_prompt(60, 6), 6)
        # Step by hand so the page table can be snapshotted while the
        # request still holds its pages (release() zeroes it).
        mid = None
        while not eng.idle:
            eng.step()
            if req.state == "decode" and mid is None and req.generated:
                mid = (req.next_pos + 1, np.array(req.page_table))
        assert req.state == "finished" and mid is not None
        # One whole-prompt prefill step (slot empty), then 5 decode
        # steps writing positions t = 6..10 -> live t+1 = 7..11 keys.
        assert eng.attn_len_samples == \
            [[0]] + [[t + 1] for t in range(6, 11)]
        pages = [eng.step_grid_info(s)["pages_live"]
                 for s in eng.attn_len_samples]
        assert pages == [[0]] + [[-(-(t + 1) // ps)]
                                 for t in range(6, 11)]
        # The visited PHYSICAL pages never include the null page.
        live, table = mid
        info = paged_grid_info(
            [live], page_size=ps,
            pages_per_seq=eng.cache.pages_per_seq,
            num_heads=eng.cache.num_heads,
            head_dim=eng.cache.head_dim,
            tables=table[None])
        assert info["pages_visited"][0] and \
            0 not in info["pages_visited"][0]

    def test_stats_attention_block_both_modes(self, params):
        """Both modes stamp the SAME static accounting (the A/B is
        honest on both sides): live pages, the gather path's constant
        bytes, and the fetch fraction."""
        for mode in ("gather", "paged"):
            eng = ServeEngine(params, ServeConfig(
                page_size=8, num_pages=32, decode_slots=2,
                prefill_chunk=4, attention=mode))
            eng.submit(_prompt(61, 5), 4)
            eng.run()
            a = eng.stats()["attention"]
            assert a["mode"] == mode
            assert a["decode_steps"] == eng.steps
            assert a["pages_full_per_step"] == \
                2 * eng.cache.pages_per_seq
            assert a["kv_bytes_per_step_gather"] > \
                a["kv_bytes_per_step_paged"] > 0
            assert 0 < a["kv_fetch_frac"] < 1

    def test_reset_metrics_clears_traffic_samples(self, params):
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=1,
            prefill_chunk=8, attention="paged"))
        eng.submit(_prompt(62, 4), 2)
        eng.run()
        assert eng.attn_len_samples
        eng.reset_metrics()
        assert eng.attn_len_samples == []
        assert eng.stats()["attention"]["kv_fetch_frac"] is None


class TestStats:
    def test_stats_shape_and_monotone_clock(self, params):
        t = [0.0]

        def clock():
            t[0] += 0.25
            return t[0]

        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=2, prefill_chunk=4),
            clock=clock)
        reqs = [eng.submit(_prompt(50 + i, 4 + i), 4) for i in range(3)]
        eng.run()
        s = eng.stats()
        assert s["by_state"] == {"finished": 3}
        assert s["generated_tokens"] == 12
        assert s["ttft_ms"]["p50"] is not None
        assert s["ttft_ms"]["p99"] >= s["ttft_ms"]["p50"]
        assert s["tbt_ms"]["p50"] is not None
        assert 0 < s["pages"]["occupancy_max"] <= 1
        for r in reqs:
            assert r.t_first_token is not None
            assert r.t_admit is not None      # eviction order keys on it
            assert r.t_finish >= r.t_first_token >= r.arrival
            assert len(r.token_times) == len(r.output)


class _Clock:
    """Settable clock: the deadline sweep reads exactly what the test
    wrote (no auto-advance), so expiry timing is deterministic."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestDeadlines:
    """Per-request deadline/TTL: a request past ``arrival + ttl`` is
    finished with the ``timeout`` status and its pages freed at the
    next engine step — one wedged or abandoned stream can never hold
    KV pages forever."""

    def _engine(self, params, clock, **cfg):
        base = dict(page_size=8, num_pages=16, decode_slots=2,
                    prefill_chunk=8)
        base.update(cfg)
        return ServeEngine(params, ServeConfig(**base), clock=clock)

    def test_decoding_request_times_out_and_frees_pages(self, params):
        clock = _Clock()
        eng = self._engine(params, clock)
        free0 = eng.cache.allocator.available
        hung = eng.submit(_prompt(0, 7), 40, ttl=5.0)   # never finishes
        live = eng.submit(_prompt(1, 6), 3)             # no deadline
        for _ in range(3):
            clock.t += 0.5
            eng.step()
        assert hung.state == "decode" and hung.pages
        clock.t = 10.0                                  # past the deadline
        eng.step()
        assert hung.state == "timeout"
        assert hung in eng.timed_out and not hung.pages
        assert hung.t_finish == 10.0
        partial = list(hung.output)
        assert partial                                  # kept what it had
        eng.run()
        assert live.state == "finished"                 # unaffected
        assert live.output == _ref(params, live.prompt, 3)
        assert hung.output == partial                   # no more tokens
        assert eng.cache.allocator.available == free0   # all pages back
        # Metrics cover the timeout, and reset drops it.
        assert eng.stats()["by_state"] == {"finished": 1, "timeout": 1}
        eng.reset_metrics()
        assert eng.timed_out == []

    @pytest.mark.slow
    def test_queued_request_can_time_out_waiting(self, params):
        clock = _Clock()
        eng = self._engine(params, clock, decode_slots=1,
                           num_pages=8, max_in_flight=1)
        a = eng.submit(_prompt(2, 6), 4)
        b = eng.submit(_prompt(3, 6), 4, ttl=1.0)       # starves in queue
        clock.t = 2.0
        eng.run()
        assert a.state == "finished"
        assert b.state == "timeout" and b.output == []
        assert b in eng.timed_out

    @pytest.mark.slow
    def test_config_default_ttl_and_override(self, params):
        clock = _Clock()
        eng = self._engine(params, clock, default_ttl=1.0)
        short = eng.submit(_prompt(4, 6), 8)            # inherits 1.0
        long = eng.submit(_prompt(5, 6), 8, ttl=100.0)  # overrides
        assert short.ttl == 1.0 and long.ttl == 100.0
        clock.t = 2.0
        eng.run()
        assert short.state == "timeout"
        assert long.state == "finished"

    def test_ttl_validation(self, params):
        with pytest.raises(ValueError, match="default_ttl"):
            ServeConfig(default_ttl=0)
        from horovod_tpu.serve.scheduler import Request

        with pytest.raises(ValueError, match="ttl"):
            Request(prompt=_prompt(7, 6), max_new_tokens=2, ttl=-1.0)


class TestUpdateParams:
    """The rolling update's engine primitive: an in-place weight swap
    that is only legal on a DRAINED engine (a live stream must never
    mix weights) and never a geometry change."""

    def test_swap_on_idle_engine_decodes_new_weights_exactly(self,
                                                             params):
        params2 = plm.init_lm_params(jax.random.PRNGKey(9), V, LMAX,
                                     LAYERS, H, DH, FFN)
        eng = ServeEngine(params, ServeConfig(page_size=8, num_pages=32,
                                              decode_slots=2,
                                              prefill_chunk=4))
        p = _prompt(50, 6)
        r1 = eng.submit(p, 6)
        eng.run()
        assert r1.output == _ref(params, p, 6)
        eng.update_params(params2)
        r2 = eng.submit(p, 6)
        eng.run()
        assert r2.output == _ref(params2, p, 6)
        # ...and the jitted step re-traced nothing (same shapes): the
        # old stream stays the old model's, the new one the new's.
        assert r1.output != r2.output or params is params2

    def test_swap_with_requests_in_flight_raises(self, params):
        eng = ServeEngine(params, ServeConfig(page_size=8, num_pages=32,
                                              decode_slots=2,
                                              prefill_chunk=4))
        eng.submit(_prompt(51, 6), 8)
        eng.step()
        with pytest.raises(RuntimeError, match="drain"):
            eng.update_params(params)
        eng.run()

    def test_geometry_change_is_a_respawn_not_a_swap(self, params):
        eng = ServeEngine(params, ServeConfig(page_size=8, num_pages=32,
                                              decode_slots=2,
                                              prefill_chunk=4))
        small = plm.init_lm_params(jax.random.PRNGKey(3), V, LMAX // 2,
                                   LAYERS, H, DH, FFN)
        with pytest.raises(ValueError, match="geometry"):
            eng.update_params(small)


class TestMeshValidation:
    """Satellite: the fail-fast truth table. Bad mesh strings die at
    ``ServeConfig`` construction; geometry that parses but cannot be
    satisfied (heads/mlp/vocab not divisible, device budget) dies at
    ``ServeEngine`` construction — NEVER at first compile. Every raise
    is :class:`InvalidArgumentError` (a ``ValueError``, so plain
    callers stay portable)."""

    @pytest.mark.parametrize("bad", [
        "garbage",            # not k=v at all
        "dp=2,tp=2",          # non-tensor axis > 1: the fleet's job
        "dp=1,tp=-1",         # wildcards not allowed: fully specified
        "tp=0",               # non-positive axis
        "",                   # empty string is not "no mesh"
    ])
    def test_bad_mesh_string_raises_at_config(self, bad):
        from horovod_tpu.common.exceptions import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                        prefill_chunk=4, mesh=bad)

    def test_heads_not_divisible_raises_at_engine(self, params):
        from horovod_tpu.common.exceptions import InvalidArgumentError
        cfg = ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                          prefill_chunk=4, mesh="dp=1,tp=3")
        with pytest.raises(InvalidArgumentError, match="num_heads"):
            ServeEngine(params, cfg)

    def test_vocab_not_divisible_raises_at_engine(self):
        from horovod_tpu.common.exceptions import InvalidArgumentError
        odd = plm.init_lm_params(jax.random.PRNGKey(9), 66, 32, 1, 4,
                                 4, 16)
        cfg = ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                          prefill_chunk=4, mesh="dp=1,tp=4")
        with pytest.raises(InvalidArgumentError, match="vocab"):
            ServeEngine(odd, cfg)

    def test_device_budget_raises_at_engine(self, params):
        from horovod_tpu.common.exceptions import InvalidArgumentError
        cfg = ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                          prefill_chunk=4, mesh="dp=1,tp=16")
        with pytest.raises(InvalidArgumentError, match="device"):
            ServeEngine(params, cfg)

    def test_valid_mesh_constructs_without_compiling(self, params):
        # Construction places params/pages but compiles nothing (jit
        # is lazy) — so this is cheap AND proves validation happened
        # already, not at first step.
        cfg = ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                          prefill_chunk=4, mesh="dp=1,tp=2")
        eng = ServeEngine(params, cfg)
        assert eng.tp == 2 and eng.logical_mesh is not None

    def test_tp_degree_property(self):
        assert ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                           prefill_chunk=4,
                           mesh="dp=1,tp=4").tp_degree == 4
        assert ServeConfig(page_size=8, num_pages=16, decode_slots=1,
                           prefill_chunk=4).tp_degree == 1


class TestTPSharding:
    """Pins on the sharded data plane itself: page placement, per-chip
    byte accounting, COW coherence, and prefix hits under tp=4."""

    def test_kv_pages_are_head_sharded(self, params):
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=4,
            mesh="dp=1,tp=4"))
        assert eng.cache.kv_sharding is not None
        for layer in eng.cache.pages:
            for kv in ("k", "v"):
                arr = layer[kv]
                assert arr.shape[2] == H
                shard = arr.addressable_shards[0].data
                assert shard.shape[2] == H // 4  # heads/tp per chip
                # every other dim stays whole on each chip
                assert (shard.shape[0], shard.shape[1], shard.shape[3]) \
                    == (arr.shape[0], arr.shape[1], arr.shape[3])

    def test_paged_grid_info_per_chip_accounting(self):
        from horovod_tpu.ops.paged_attention import paged_grid_info
        kw = dict(page_size=8, pages_per_seq=8, num_heads=4,
                  head_dim=4, dtype_bytes=4, num_layers=2)
        one = paged_grid_info([17, 3], tp=1, **kw)
        four = paged_grid_info([17, 3], tp=4, **kw)
        assert one["kv_bytes_per_chip"] == one["kv_bytes"]
        assert four["kv_bytes_per_chip"] == one["kv_bytes"] // 4
        assert (four["kv_bytes_gather_per_chip"]
                == one["kv_bytes_gather"] // 4)
        assert four["tp"] == 4
        # same traffic model, only the per-chip slice changes
        assert four["kv_bytes"] == one["kv_bytes"]
        with pytest.raises(ValueError, match="divide"):
            paged_grid_info([17], tp=3, **kw)

    def test_attention_stats_carry_per_chip_bytes(self, params):
        eng = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=8,
            mesh="dp=1,tp=4"))
        eng.submit(_prompt(60, 5), 4)
        eng.run()
        attn = eng.stats()["attention"]
        assert attn["tp"] == 4
        # gather mode reconstructs the full table; per-chip is 1/tp
        assert attn["kv_bytes_per_chip"] == pytest.approx(
            attn["kv_bytes_per_step_gather"] / 4, rel=1e-6)

    def test_prefix_hits_and_cow_stay_sharded(self, params):
        """Prefix-cache hits under tp=4 reuse head-sharded pages, the
        streams stay bit-identical to the unsharded engine, and a
        copy-on-write of a shared sharded page lands on every chip
        (COW-under-sharding coherence pin)."""
        sys_p = _prompt(61, 16)
        mk = lambda mesh: ServeEngine(params, ServeConfig(
            page_size=8, num_pages=32, decode_slots=1, prefill_chunk=8,
            prefix_caching=True, mesh=mesh))
        outs = {}
        for mesh in MESHES:
            eng = mk(mesh)
            reqs = [eng.submit(
                np.concatenate([sys_p, _prompt(62 + i, 3)]), 4)
                    for i in range(2)]
            eng.run()
            assert eng.prefix_stats()["hits"] >= 1
            outs[mesh] = [r.output for r in reqs]
            if mesh is not None:
                spec = eng.cache.kv_sharding.spec
                live = eng.cache.pages[0]["k"]
                new = eng.cache.cow_page(1)
                for layer in eng.cache.pages:
                    for kv in ("k", "v"):
                        assert layer[kv].sharding.spec == spec
                # the copy really happened, on-device and sharded
                got = np.asarray(eng.cache.pages[0]["k"][new])
                np.testing.assert_array_equal(
                    got, np.asarray(live[1]))
        assert outs[None] == outs["dp=1,tp=4"]


def _spec_cfg(k, attention="gather", mesh=None, **kw):
    base = dict(page_size=8, num_pages=40, decode_slots=2,
                prefill_chunk=4, speculate_k=k, draft_layers=1,
                attention=attention, mesh=mesh)
    base.update(kw)
    return ServeConfig(**base)


@pytest.mark.parametrize("mesh", MESHES, ids=MESH_IDS)
@pytest.mark.parametrize("attention", ["gather", "paged"])
class TestSpeculativeExactness:
    """The round-19 acceptance pin: with ``speculate_k > 0`` every
    greedy stream is BIT-IDENTICAL to ``lm_decode`` AND (therefore) to
    the non-speculative engine — across the same attention AND mesh
    matrix as TestGreedyExactness, for every window size ``k``. The
    draft is the layer-skip view (target's first layer here), so a
    wrong draft can only cost speedup, never tokens. The k=1/k=4
    cells are slow-marked in tests/conftest.py; the k=2 cells stay
    fast in all four attention×mesh combinations as the named
    stand-ins."""

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_spec_stream_bit_identical(self, params, attention, mesh,
                                       k):
        spec = [(5, 9), (9, 6), (3, 11)]
        prompts = [_prompt(70 + i, lp) for i, (lp, _) in enumerate(spec)]
        refs = [_ref(params, p, n) for p, (_, n) in zip(prompts, spec)]
        eng = ServeEngine(params, _spec_cfg(k, attention, mesh))
        reqs = [eng.submit(prompts[0], spec[0][1]),
                eng.submit(prompts[1], spec[1][1])]
        for _ in range(2):
            eng.step()               # third request joins mid-flight
        reqs.append(eng.submit(prompts[2], spec[2][1]))
        eng.run()
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref
        sp = eng.stats()["spec"]
        assert sp["k"] == k and sp["ticks"] > 0
        assert sp["tokens_per_step"] is not None


class TestSpeculativeLifecycle:
    """Spec-path composition pins that don't need the full matrix:
    budget clamping, EOS mid-window, eviction-recompute and prefix COW
    under speculation, the acceptance accounting, and config
    validation. One attention mode each — the matrix above already
    pins both modes' token streams."""

    def test_budget_clamp_never_overshoots(self, params):
        """k=4 against max_new_tokens in {1, 2, 3}: the per-slot
        window clamp (Request.spec_window) must stop the stream at
        EXACTLY the budget — a speculative window may never emit past
        max_new_tokens. n=1 finishes at prefill (spec_window 0), n=3
        clamps mid-stream."""
        for n in (1, 3):
            prompt = _prompt(80, 7)
            eng = ServeEngine(params, _spec_cfg(4))
            req = eng.submit(prompt, n)
            eng.run()
            assert req.state == "finished"
            assert req.output == _ref(params, prompt, n)

    def test_eos_mid_window_truncates(self, params):
        """An EOS accepted in the middle of a window stops the stream
        AT the EOS — later accepted rows of the same window must be
        discarded, exactly like the sequential engine."""
        prompt = _prompt(3, 6)
        full = _ref(params, prompt, 8)
        eos = full[2]
        stop = full.index(eos) + 1
        eng = ServeEngine(params, _spec_cfg(4))
        req = eng.submit(prompt, 8, eos_token=eos)
        eng.run()
        assert req.state == "finished"
        assert req.output == full[:stop]

    def test_eviction_recompute_stays_exact_under_spec(self, params):
        """Lazy admission under page pressure WITH speculation: the
        widened page grant (next_pos + spec_window) makes eviction
        pressure harsher, and a re-prefilled request must still
        produce the lm_decode stream."""
        spec = [(9, 10), (11, 8), (10, 9)]
        prompts = [_prompt(30 + i, lp) for i, (lp, _) in enumerate(spec)]
        refs = [_ref(params, p, n) for p, (_, n) in zip(prompts, spec)]
        eng = ServeEngine(params, _spec_cfg(
            2, attention="paged", page_size=4, num_pages=8,
            admission="lazy"))
        reqs = [eng.submit(p, n) for p, (_, n) in zip(prompts, spec)]
        eng.run(max_steps=500)
        assert sum(r.evictions for r in reqs) > 0, \
            "test must exercise the eviction path"
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert req.output == ref

    def test_prefix_cow_stays_exact_under_spec(self, params):
        """Prefix-cache hits + COW under speculation: the widened
        _cow_guard must copy a shared page BEFORE the verify window
        writes into it, so prefix-mates stay bit-identical to the cold
        lm_decode stream."""
        sys_p = _prompt(61, 16)
        eng = ServeEngine(params, _spec_cfg(2, prefix_caching=True,
                                            decode_slots=1))
        prompts = [np.concatenate([sys_p, _prompt(62 + i, 3)])
                   for i in range(2)]
        reqs = [eng.submit(p, 4) for p in prompts]
        eng.run()
        assert eng.prefix_stats()["hits"] >= 1
        for req, p in zip(reqs, prompts):
            assert req.state == "finished"
            assert req.output == _ref(params, p, 4)

    def test_full_depth_draft_accepts_everything(self, params):
        """draft_layers == n_layers makes the draft ≡ the target, so
        every proposal is accepted by construction: accept_rate is
        EXACTLY 1.0 and tokens_per_step > 1 — the deterministic CI pin
        that the multi-token fast path actually engages."""
        prompt = _prompt(81, 6)
        eng = ServeEngine(params, _spec_cfg(4, draft_layers=LAYERS,
                                            decode_slots=1))
        req = eng.submit(prompt, 9)
        eng.run()
        assert req.output == _ref(params, prompt, 9)
        sp = eng.stats()["spec"]
        assert sp["accept_rate"] == 1.0
        assert sp["proposed"] == sp["accepted"] > 0
        assert sp["tokens_per_step"] > 1.0

    def test_draft_layers_auto_default(self, params):
        """draft_layers=0 = auto (half the stack). Construction-only:
        the engine resolves the depth before anything compiles, and
        the exactness matrix above already runs explicit depths."""
        eng = ServeEngine(params, _spec_cfg(2, draft_layers=0))
        assert eng.draft_layers == max(1, LAYERS // 2)
        assert eng.spec_stats()["draft_layers"] == LAYERS // 2

    def test_temperature_same_seed_deterministic(self, params):
        """temp>0 under speculation: the position-folded rejection
        sampling is deterministic per seed (two identical runs agree),
        and every token is in-vocab. NOT pinned vs the non-spec
        engine — window alignment legitimately changes which folded
        key draws each position's uniform."""
        prompt = _prompt(83, 5)
        outs = []
        for _ in range(2):
            eng = ServeEngine(params, _spec_cfg(2, decode_slots=1))
            req = eng.submit(prompt, 6, temperature=0.8, top_k=8,
                             seed=42)
            eng.run()
            assert req.state == "finished"
            outs.append(req.output)
        assert outs[0] == outs[1]
        assert all(0 <= t < V for t in outs[0])

    def test_greedy_neighbor_unaffected_by_sampling_slot(self, params):
        """A greedy stream sharing speculative steps with a
        temperature stream stays bit-identical to lm_decode."""
        pg, ps = _prompt(7, 6), _prompt(8, 6)
        ref = _ref(params, pg, 6)
        eng = ServeEngine(params, _spec_cfg(2))
        rg = eng.submit(pg, 6)
        rs = eng.submit(ps, 6, temperature=1.2, top_k=4, seed=9)
        eng.run()
        assert rg.output == ref
        assert all(0 <= t < V for t in rs.output)

    def test_spec_stats_block_shape_and_reset(self, params):
        eng = ServeEngine(params, _spec_cfg(2))
        req = eng.submit(_prompt(84, 5), 5)
        eng.run()
        sp = eng.stats()["spec"]
        assert set(sp) == {"k", "draft_layers", "ticks", "proposed",
                           "accepted", "accept_rate", "tokens_per_step"}
        assert sp["ticks"] > 0 and sp["proposed"] >= sp["accepted"] >= 0
        # emitted tokens per tick can never exceed the window
        assert 1.0 <= sp["tokens_per_step"] <= sp["k"] + 1
        assert req.output == _ref(params, req.prompt, 5)
        eng.reset_metrics()
        sp = eng.spec_stats()
        assert sp["ticks"] == sp["proposed"] == sp["accepted"] == 0
        assert sp["accept_rate"] is None
        # the non-speculative engine has NO spec block at all
        base = ServeEngine(params, ServeConfig(
            page_size=8, num_pages=16, decode_slots=1, prefill_chunk=4))
        assert "spec" not in base.stats()
        assert base.spec_stats() is None

    def test_spec_engine_compiles_once(self, params):
        """Join/leave across speculative steps never recompiles: the
        widened step programs are shape-stable (width rides data, not
        shape)."""
        eng = ServeEngine(params, _spec_cfg(2))
        for i in range(4):
            eng.submit(_prompt(85 + i, 3 + i), 3 + i)
        eng.run()
        if not hasattr(eng._step_mixed, "_cache_size"):
            pytest.skip("no jit cache introspection on this jax")
        mixed = eng._step_mixed._cache_size()
        decode = eng._step_decode._cache_size()
        assert mixed <= 1 and decode <= 1 and mixed + decode >= 1

    def test_config_validation(self, params):
        with pytest.raises(ValueError, match="speculate_k"):
            ServeConfig(speculate_k=-1)
        with pytest.raises(ValueError, match="draft_layers"):
            ServeConfig(draft_layers=1)       # without speculate_k
        with pytest.raises(ValueError, match="draft_layers"):
            ServeConfig(speculate_k=2, draft_layers=-1)
        # draft deeper than the target dies at engine construction
        with pytest.raises(ValueError, match="draft"):
            ServeEngine(params, _spec_cfg(2, draft_layers=LAYERS + 1))


class TestSpeculativeAcceptUnit:
    """Host-side pins on the acceptance rule itself
    (serve.sampling.speculative_accept) — no engine, no compile: the
    fast stand-ins for the slow-marked temperature e2e."""

    def _rows(self, w, vocab=16, seed=0):
        rng = np.random.default_rng(seed)
        return rng.standard_normal((w, vocab)).astype(np.float32)

    def test_greedy_longest_agreeing_prefix(self):
        from horovod_tpu.serve.sampling import speculative_accept

        tl = self._rows(4)
        tgt = [int(np.argmax(r)) for r in tl]
        # drafts agree at rows 0,1 then diverge at row 2: emit the two
        # agreed tokens plus the row-2 correction, nothing after.
        draft = np.asarray([tgt[0], tgt[1], (tgt[2] + 1) % 16],
                           np.int32)
        out = speculative_accept(tl, draft, self._rows(3, seed=1),
                                 temperature=0.0, top_k=0, seed=0,
                                 position0=5)
        assert out == tgt[:3]

    def test_greedy_all_accepted_emits_bonus(self):
        from horovod_tpu.serve.sampling import speculative_accept

        tl = self._rows(4, seed=2)
        tgt = [int(np.argmax(r)) for r in tl]
        out = speculative_accept(tl, np.asarray(tgt[:3], np.int32),
                                 self._rows(3, seed=3),
                                 temperature=0.0, top_k=0, seed=0,
                                 position0=0)
        assert out == tgt          # k accepted + the bonus row

    def test_greedy_first_mismatch_emits_one(self):
        from horovod_tpu.serve.sampling import speculative_accept

        tl = self._rows(3, seed=4)
        tgt = [int(np.argmax(r)) for r in tl]
        draft = np.asarray([(tgt[0] + 1) % 16, tgt[1]], np.int32)
        out = speculative_accept(tl, draft, self._rows(2, seed=5),
                                 temperature=0.0, top_k=0, seed=0,
                                 position0=0)
        assert out == tgt[:1]      # the correction alone

    def test_stochastic_deterministic_and_window_bounded(self):
        from horovod_tpu.serve.sampling import speculative_accept

        tl = self._rows(5, seed=6)
        draft = np.asarray([3, 7, 1, 9], np.int32)
        dl = self._rows(4, seed=7)
        kw = dict(temperature=0.8, top_k=8, seed=42, position0=11)
        a = speculative_accept(tl, draft, dl, **kw)
        b = speculative_accept(tl, draft, dl, **kw)
        assert a == b              # same folded keys, same stream
        assert 1 <= len(a) <= 5    # never empty, never past the window
        assert all(0 <= t < 16 for t in a)
        # a different seed may disagree, a different position0 must
        # still emit a valid stream (position-folded keys)
        c = speculative_accept(tl, draft, dl, temperature=0.8, top_k=8,
                               seed=42, position0=12)
        assert 1 <= len(c) <= 5
