#!/usr/bin/env python
"""Canonical scaling benchmark: ResNet-50 synthetic data, Horovod protocol.

Mirrors the reference's benchmark protocol exactly
(reference examples/pytorch_synthetic_benchmark.py:79-110): warmup
iterations, then ``num_iters`` timed groups of ``num_batches_per_iter``
training steps; report images/sec ± CI. TPU-native execution: the whole
step (fwd + bwd + fused gradient allreduce + update) is one XLA program
run over a 1-D "hvd" mesh of every visible chip.

Prints ONE JSON line:
    {"metric": "resnet50_img_per_sec_per_chip", "value": N,
     "unit": "img/sec/chip", "vs_baseline": N, "peak": N,
     "probe_tflops": N}

``peak`` is the best timed window's rate — on a shared/tunneled chip it
bounds what the program does when the device is actually ours, while
``value`` (the mean) stays the protocol's headline number.
``probe_tflops`` stamps the chip's measured matmul rate at bench time
(see ``probe_chip``) so a low headline number is attributable to
contention rather than a regression. Degraded records carry the same
keys with null values plus an ``"error"`` field.

``vs_baseline`` compares against the reference's published per-GPU
absolute throughput: 1656.82 img/s over 16 Pascal GPUs = 103.55 img/s/GPU
(reference docs/benchmarks.md:22-38) — the only absolute number the
reference publishes.

``--model transformer_lm`` switches to the long-context lane the
reference never had: causal-LM training, tokens/sec/chip (vs_baseline
null — the reference published no LM number).

Outage resilience: the measurement runs in a supervised child process.
A flapping backend tunnel can make ``jax.devices()`` hang indefinitely
or return UNAVAILABLE mid-init — neither is recoverable in-process (a
hung PJRT client cannot be re-initialized), so the parent enforces a
wall-clock timeout per attempt, retries with exponential backoff
(HVD_BENCH_ATTEMPTS / HVD_BENCH_ATTEMPT_TIMEOUT / HVD_BENCH_BACKOFF),
and on final failure STILL prints the one-line JSON contract with an
``"error"`` field and exits 0 — the official record degrades to a
parseable diagnosis, never a stack trace.
"""

import argparse
import json
import os
import sys

# Hermetic CI mode: force an 8-device virtual CPU mesh before jax
# initializes (the sandbox's sitecustomize consumes JAX_PLATFORMS) so the
# driver entry itself is testable without a chip. Only the measuring
# process pays the jax import — the supervisor parent never touches a
# backend.
if os.environ.get("HVD_TPU_FORCE_CPU") and (
        "--_child" in sys.argv or os.environ.get("HVD_BENCH_NO_SUPERVISOR")
        or os.environ.get("HOROVOD_RANK") is not None):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
import time

# Child exit code for failures that retrying cannot fix (unknown model,
# bad CLI combination) — the supervisor fails fast on these instead of
# burning attempts and backoff on a deterministic crash.
_RC_DETERMINISTIC = 3

# The reference publishes exactly one absolute throughput: ResNet-101 at
# 1656.82 img/s over 16 Pascal GPUs (reference docs/benchmarks.md:22-38).
# BASELINE.md calibrates the ResNet-50 north star against the same number
# (ResNet-class, bs=64/device). Other models have no published reference
# throughput, so their JSON carries vs_baseline=null rather than an
# apples-to-oranges ratio.
_REF_PER_DEVICE = 1656.82 / 16.0
REFERENCE_BASELINES = {"resnet50": _REF_PER_DEVICE, "resnet101": _REF_PER_DEVICE}


def probe_chip(log):
    """~20 ms bf16 matmul probe: sustained TFLOP/s stamped into the JSON
    record as ``probe_tflops``. The absolute headline throughput on a
    shared/tunneled chip swings 5x with contention (PERF_RUNS.tsv shows
    8.5k-42k img/s for the same program); this stamp quantifies the
    chip's condition AT MEASUREMENT TIME so a degraded number reads as
    "loaded chip", not "regression". Chained matmuls (each feeding the
    next) so the device, not the dispatch path, is what's timed."""
    import jax
    import jax.numpy as jnp

    # Accelerator sizing. The hermetic-CI CPU mesh gets a token probe
    # instead: 3.4 TFLOP of matmuls is ~30 s of host CPU, and the stamp
    # only means something on real hardware anyway.
    if jax.devices()[0].platform == "cpu":
        n, n1, n2 = 512, 2, 6
    else:
        n, n1, n2 = 4096, 25, 100
    x = (jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32)
         / jnp.sqrt(n)).astype(jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    # Warm + FORCE REAL SYNC (the axon trap, see run_timed): without a
    # d2h pull first, this probe times dispatch, not the device — the
    # pre-round-5 stamps read 3,000-16,000 "TFLOP/s" on a chip whose
    # true sustained rate is ~180 TFLOP/s.
    _force_sync(f(x))

    def chain(iters):
        t0 = time.perf_counter()
        y = x
        for _ in range(iters):
            y = f(y)
        jax.block_until_ready(y)
        return time.perf_counter() - t0

    # MARGINAL rate over two chain lengths: each synced chain carries a
    # fixed ~65 ms tunnel round-trip/sync overhead that a single short
    # chain folds into the average (25 iters read 41 TF on a chip whose
    # marginal rate is ~180 TF); the difference quotient cancels it.
    t1, t2 = chain(n1), chain(n2)
    if t2 <= t1:
        # Timer noise on a loaded host can invert short CPU chains; an
        # inverted delta would clamp into an absurd stamp — the exact
        # failure class this probe was rebuilt to eliminate. A null
        # stamp reads as "probe unreliable", never as a fast chip.
        log(f"Chip probe UNRELIABLE: chain({n2})={t2:.4f}s <= "
            f"chain({n1})={t1:.4f}s", file=sys.stderr)
        return None
    tflops = 2 * n**3 * (n2 - n1) / (t2 - t1) / 1e12
    log(f"Chip probe: {tflops:.1f} TFLOP/s sustained "
        f"(bf16 {n}^3 matmul, marginal over {n1}->{n2} chained)",
        file=sys.stderr)
    return round(tflops, 1)


def _force_sync(tree) -> None:
    """Pull one scalar off-device so block_until_ready means what it
    says on the axon tunnel (see the sync-trap note in run_timed).
    Shared implementation: horovod_tpu/utils/devsync.py."""
    from horovod_tpu.utils.devsync import force_device_sync

    force_device_sync(tree)


def run_timed(run_step, state, batch, args, units_per_iter, unit, log):
    """The reference's measurement discipline: warmup (compile included),
    then ``num_iters`` timed windows of ``num_batches_per_iter`` steps,
    ONE device sync per window."""
    import jax
    import numpy as np

    if getattr(args, "compile_only", False):
        # Warm-cache lane: pay the first compile (writing the persistent
        # cache entry if the backend serializes) and exit — so a big
        # model's MEASURED lane reruns against a warm cache instead of
        # burning its window on XLA (vgg16 first-compiles exceeded every
        # round-3 lane budget; tools/hw_sweep.py runs this lane first).
        t0 = time.perf_counter()
        state, _ = run_step(state, batch)
        _force_sync(state)  # real first-step time, not dispatch (axon trap)
        secs = time.perf_counter() - t0
        log(f"compile-only: first step (compile included) {secs:.1f}s",
            file=sys.stderr)
        return round(secs, 2), 0.0, round(secs, 2)

    for _ in range(args.num_warmup_batches):
        state, _ = run_step(state, batch)
    jax.block_until_ready(state)
    # AXON SYNC TRAP (PERF.md round 5): on the tunneled backend,
    # block_until_ready does NOT wait for device execution until the
    # process has performed one device->host transfer — before that,
    # "timed" windows measure async dispatch only (~19x too fast for
    # the ResNet lane; every pre-round-5 absolute number carried this).
    # One scalar pull here flips the process into real-synchronization
    # semantics: chained dispatch still pipelines (measured: marginal
    # per-step time matches profiler device time), and each window's
    # block_until_ready below then observes true completion.
    _force_sync(state)

    rates = []
    for x in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, _ = run_step(state, batch)
        jax.block_until_ready(state)
        elapsed = time.perf_counter() - t0
        rate = units_per_iter / elapsed
        log(f"Iter #{x}: {rate:.1f} {unit} per chip", file=sys.stderr)
        rates.append(rate)

    mean = float(np.mean(rates))
    conf = float(1.96 * np.std(rates))
    log(f"{unit} per chip: {mean:.1f} +-{conf:.1f}", file=sys.stderr)
    if conf > 0.1 * mean:
        # A shared/tunneled chip under load produces window-to-window
        # swings far beyond the protocol's CI on a quiet machine; flag it
        # so a low absolute number isn't mistaken for a regression.
        log(f"WARNING: high variance (CI {conf:.0f} vs mean {mean:.0f}) — "
            "noisy/shared chip; rerun on a quiet machine for a "
            "representative number", file=sys.stderr)
    # The best window is the least-contended observation: on a shared/
    # tunneled chip it bounds what the program can do when the device is
    # actually ours, while the mean stays the protocol's headline number.
    return mean, conf, float(np.max(rates))


def measure_snapshot_ms(state, log, samples: int = 3):
    """Measured cost of ONE elastic host-RAM snapshot of ``state``
    (synchronous d2h through horovod_tpu.elastic.Snapshotter), in ms.

    Min over ``samples`` takes: the steady-state cost is what the
    cadence amortizes — a one-off allocator warmup in the mean would
    overstate the overhead. Runs BEFORE the timed windows (the state is
    donated inside them); gradients share the state's shapes so the d2h
    cost is the same one training would pay."""
    import jax

    from horovod_tpu.elastic.snapshot import Snapshotter

    jax.block_until_ready(state)
    snap = Snapshotter(every=1)
    times = []
    for i in range(samples):
        t0 = time.perf_counter()
        snap.take(i + 1, state, sync=True)
        times.append((time.perf_counter() - t0) * 1e3)
    ms = min(times)
    log(f"Snapshot probe: {ms:.2f} ms per sync host-RAM snapshot "
        f"(min of {samples})", file=sys.stderr)
    return ms


def snapshot_field(args, snap_ms, mean, units_per_step):
    """The ``"snapshot"`` JSON stamp: cadence, ms/snapshot and measured
    overhead %% of step time — the elastic acceptance evidence (budget:
    <= 2%% at the default cadence; docs/elastic.md cadence math).
    ``mean`` is the measured rate in units/sec; ``units_per_step``
    converts it to a per-training-step time."""
    if snap_ms is None:
        return {"snapshot": None}
    field = {"every": args.snapshot_every,
             "ms_per_snapshot": round(snap_ms, 3)}
    if mean and mean > 0:
        step_secs = units_per_step / mean
        overhead = (100.0 * (snap_ms / 1e3)
                    / (args.snapshot_every * step_secs))
        # 3 significant digits at ANY magnitude: fixed-decimal rounding
        # would floor a tiny-but-real overhead (fast steps on a quiet
        # host) to exactly 0.0, misreporting the measured cost the
        # stamp exists to evidence.
        field["overhead_pct"] = float(f"{overhead:.3g}")
    else:
        field["overhead_pct"] = None
    return {"snapshot": field}


def apply_window(step_fn, batch, steps_per_dispatch):
    """Window-lane wiring (--steps-per-dispatch K): one-call delegate to
    the shared synthetic-window stager so the bench and the profiler
    (tools/profile_step.py) always dispatch the same window shape."""
    from horovod_tpu.jax.window import stage_synthetic_window

    return stage_synthetic_window(step_fn, batch, steps_per_dispatch)


def bench_image(args, log):
    """ResNet/VGG/Inception/ViT lane: img/sec/chip."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hvd
    from horovod_tpu import models

    n = hvd.size()
    batch_size = args.batch_size if args.batch_size is not None else 64
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    for flag in ("fused_ce", "scan_layers", "remat", "flash_attention",
                 "flash_full_grid"):
        if getattr(args, flag):
            raise ValueError(
                f"--{flag.replace('_', '-')} applies to transformer_lm "
                f"only (got --model {args.model})")
    if args.attention is not None:
        raise ValueError(
            f"--attention applies to transformer_lm only "
            f"(got --model {args.model})")
    if args.flash_bwd is not None:
        raise ValueError(
            f"--flash-bwd applies to transformer_lm only "
            f"(got --model {args.model})")
    build_kwargs = {}
    if args.fused_bn:
        name = args.model.lower()
        if not (name.startswith("resnet") or name.startswith("inception")):
            raise ValueError(
                "--fused-bn applies to the ResNet and Inception families")
        build_kwargs["fused_bn"] = True
    model = models.build(args.model, num_classes=1000, dtype=dtype,
                         **build_kwargs)
    k = args.steps_per_dispatch
    rng = jax.random.PRNGKey(42)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    sgd = optax.sgd(
        0.01, momentum=0.9,
        accumulator_dtype=jnp.bfloat16 if args.bf16_momentum else None)
    state, optimizer = models.create_train_state(
        rng, model, sgd, sample, zero=args.zero, overlap=args.overlap,
        compression=resolve_compression(args),
        hierarchical=args.hierarchical)
    step_fn = models.make_train_step(model, optimizer, average_loss=False)
    # state_partition_specs owns the sharded-vs-replicated knowledge
    # (ZeRO flats, EF residuals -> P("hvd"); everything else P()).
    state_spec = models.state_partition_specs(state)

    global_batch = batch_size * n
    batch = {
        "image": jax.random.normal(
            rng, (global_batch, args.image_size, args.image_size, 3),
            jnp.float32),
        "label": jax.random.randint(rng, (global_batch,), 0, 1000),
    }

    # One prebuilt compiled handle — no per-step cache lookup/hashing — with
    # the train state donated so XLA updates weights/momenta in place
    # instead of reallocating ~100 MB every step. With
    # --steps-per-dispatch K > 1 the handle is a lax.scan window of K
    # steps over a device-staged K-batch stack: one dispatch and one
    # sync per window amortizes the measured per-step host gap
    # (PERF.md round 5; horovod_tpu/jax/window.py).
    step_fn, batch, batch_spec = apply_window(step_fn, batch, k)
    run_step = hvd.spmd_fn(
        step_fn,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        donate_argnums=(0,),
    )
    log(f"Model: {args.model}, batch size {batch_size}/chip, {n} chips "
        f"({jax.devices()[0].platform})"
        + (f", {k}-step dispatch windows" if k > 1 else ""),
        file=sys.stderr)
    stamp = overlap_stamp(args, state, log)
    stamp.update(wire_stamp(args, state, log))
    stamp.update(collectives_stamp(run_step, state, batch, log))
    snap_ms = (measure_snapshot_ms(state, log)
               if args.snapshot_every > 0 and not args.compile_only
               else None)
    units_per_iter = batch_size * k * args.num_batches_per_iter
    mean, conf, peak = run_timed(run_step, state, batch, args,
                                 units_per_iter, "img/sec", log)
    if not args.compile_only:
        log(f"Total img/sec on {n} chip(s): {mean * n:.1f} +-{conf * n:.1f}",
            file=sys.stderr)
    metric, unit = metric_contract(args)
    stamp = {**stamp, **snapshot_field(args, snap_ms, mean, batch_size)}
    return mean, peak, unit, metric, stamp


def bench_lm(args, log):
    """Long-context causal-LM lane: tokens/sec/chip (beyond the
    reference, which scaled batch only — SURVEY §2.9/§5)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hvd
    from horovod_tpu import models

    if args.fused_bn:
        raise ValueError(
            "--fused-bn applies to the ResNet and Inception families "
            "(got --model transformer_lm)")
    n = hvd.size()
    # sequences per chip
    batch_size = args.batch_size if args.batch_size is not None else 8
    L = args.seq_len
    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    attn_fn = None
    attention = resolve_attention(args)
    flash_grid = None
    if attention == "flash":
        # Pallas flash attention (ops/attention.py): the O(L)-memory
        # kernel lane, A/B-able against the default dense attention at
        # the same protocol (VERDICT r2 item 6's throughput comparison).
        from horovod_tpu.ops.attention import (flash_attention,
                                               flash_grid_info,
                                               resolve_bwd_impl)

        block = min(128, L)
        if L % block:
            raise ValueError(
                f"flash attention needs --seq-len divisible by the "
                f"kernel block ({block}); got {L} — the dense lane "
                f"accepts any length, pad or round for the A/B")
        # --flash-full-grid pins the causal grid to full size (compute-
        # skip only) for the truncated-vs-full A/B lanes; the default
        # (None) runs the packed at-or-below-diagonal grid. --flash-bwd
        # pins the backward implementation: below Lk 8192 "auto" runs
        # the scan backward, which is diagonal-truncated by
        # construction on BOTH sides of the grid A/B — pinning "pallas"
        # makes the A/B span the backward kernels too. The unset
        # default (None) keeps the HVD_FLASH_BWD env override working
        # exactly as it did before this flag existed.
        truncate = False if args.flash_full_grid else None
        bwd = args.flash_bwd

        def attn_fn(q, k, v):
            return flash_attention(q, k, v, causal=True, truncate=truncate,
                                   bwd_impl=bwd)

        # Grid + K/V-DMA accounting stamped into the JSON record so the
        # wall time is attributable to a concrete grid (blocks, step
        # count, bytes) and a named backward, not just a lane name.
        # PER-CHIP numbers (batch_size is per chip), mirroring each
        # device's actual pallas grid — like the tokens/sec/chip metric
        # the record headlines.
        flash_grid = flash_grid_info(
            L, L, causal=True, truncate=truncate,
            head_dim=args.lm_dim // args.lm_heads,
            batch_heads=batch_size * args.lm_heads,
            dtype_bytes=4 if args.fp32 else 2)
        flash_grid["bwd"] = resolve_bwd_impl(bwd, L)
    elif args.flash_full_grid:
        raise ValueError("--flash-full-grid requires the flash attention "
                         "path (--attention flash, or auto at long seq)")
    elif args.flash_bwd is not None:
        raise ValueError("--flash-bwd requires the flash attention "
                         "path (--attention flash, or auto at long seq)")
    model = models.TransformerLM(
        vocab_size=args.vocab, num_layers=args.lm_layers,
        num_heads=args.lm_heads, embed_dim=args.lm_dim,
        max_len=max(L, 2048), dtype=dtype, attn_fn=attn_fn,
        scan_layers=args.scan_layers, remat=args.remat)
    rng = jax.random.PRNGKey(42)
    sample = jnp.zeros((1, L), jnp.int32)
    # --bf16-momentum maps to adam's first-moment dtype on this lane (the
    # second moment stays fp32 for stability).
    opt = optax.adam(
        1e-4, mu_dtype=jnp.bfloat16 if args.bf16_momentum else None)
    state, optimizer = models.create_train_state(
        rng, model, opt, sample, zero=args.zero, overlap=args.overlap,
        compression=resolve_compression(args),
        hierarchical=args.hierarchical)
    state_spec = models.state_partition_specs(state)

    def step_fn(state, batch):
        tokens = batch["tokens"]

        if args.fused_ce:
            # Chunked fused loss (ops/xent.py): the [B, L, vocab] fp32
            # logits tensor — the step's largest single HBM sink —
            # never materializes; the vocab projection's gradient comes
            # out of the same scan.
            from horovod_tpu.ops.xent import fused_cross_entropy

            def loss_fn(params):
                hidden = model.apply({"params": params}, tokens,
                                     train=False, return_hidden=True)
                e = hidden.shape[-1]
                h = hidden[:, :-1].reshape(-1, e).astype(jnp.float32)
                wv = params["lm_head"]["kernel"].astype(jnp.float32)
                return fused_cross_entropy(h, wv, tokens[:, 1:].reshape(-1))
        else:
            def loss_fn(params):
                logits = model.apply({"params": params}, tokens,
                                     train=False)
                logp = jax.nn.log_softmax(
                    logits[:, :-1].astype(jnp.float32))
                tgt = tokens[:, 1:]
                nll = -jnp.take_along_axis(logp, tgt[..., None], -1)
                return jnp.mean(nll)

        loss, grads = jax.value_and_grad(loss_fn)(state["params"])
        return models.apply_gradients(optimizer, state, grads), loss

    batch = {"tokens": jax.random.randint(
        rng, (batch_size * n, L), 0, args.vocab)}
    k = args.steps_per_dispatch
    step_fn, batch, batch_spec = apply_window(step_fn, batch, k)
    run_step = hvd.spmd_fn(
        step_fn,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        donate_argnums=(0,),
    )
    grid_note = ""
    if flash_grid is not None:
        grid_note = (f", grid {flash_grid['steps']}/"
                     f"{flash_grid['steps_full']} steps "
                     f"({'truncated' if flash_grid['truncated'] else 'full'}"
                     f", {flash_grid['block_q']}x{flash_grid['block_k']})")
    log(f"Model: transformer_lm ({args.lm_layers}L/{args.lm_dim}d), "
        f"seq {L}, batch {batch_size} seqs/chip, {n} chips "
        f"({jax.devices()[0].platform}), {attention} attention{grid_note}"
        + (f", {k}-step dispatch windows" if k > 1 else ""),
        file=sys.stderr)
    units_per_iter = batch_size * L * k * args.num_batches_per_iter
    stamp = overlap_stamp(args, state, log)
    stamp.update(wire_stamp(args, state, log))
    stamp.update(collectives_stamp(run_step, state, batch, log))
    snap_ms = (measure_snapshot_ms(state, log)
               if args.snapshot_every > 0 and not args.compile_only
               else None)
    mean, conf, peak = run_timed(run_step, state, batch, args,
                                 units_per_iter, "tokens/sec", log)
    if not args.compile_only:
        log(f"Total tokens/sec on {n} chip(s): {mean * n:.1f} "
            f"+-{conf * n:.1f}", file=sys.stderr)
    metric, unit = metric_contract(args)
    stamp = {**stamp,
             **snapshot_field(args, snap_ms, mean, batch_size * L)}
    return mean, peak, unit, metric, {"attention": attention,
                                      "flash_grid": flash_grid,
                                      **stamp}


def resolve_compression(args):
    """The Compression class the lane runs (and stamps)."""
    from horovod_tpu.jax.compression import Compression

    return getattr(Compression, args.compression or "none")


def wire_leaves(leaves, compression):
    """The leaves ``fused_reduce`` actually buckets: the compressor's
    own ``plan_dtype`` rule (cast compressors halve floating leaves
    BEFORE planning; none/int8/fp8 plan the raw tree), so the stamp's
    plan can never drift from the executing one."""
    import jax

    out = []
    changed = False
    for l in leaves:
        pd = compression.plan_dtype(l.dtype)
        if pd == l.dtype:
            out.append(l)
        else:
            out.append(jax.ShapeDtypeStruct(l.shape, pd))
            changed = True
    return out if changed else leaves


def wire_stamp(args, state, log):
    """The ``"hierarchical"``/``"wire"`` evidence fields: the resolved
    ladder knob (mode + inner) and the per-leg static byte split
    (fusion.hier_wire_summary — ICI vs DCN operand bytes, DCN wire
    dtype, compression ratio), so a multi-slice A/B row carries the
    bytes its prediction (tools/scaling_model.py) is priced on. Null
    wire when the ladder is not engaged (single-slice default)."""
    import jax

    import horovod_tpu.jax as hvd
    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax.fusion import (
        hier_wire_summary,
        plan_buckets,
        resolve_hierarchical,
    )

    mode = args.hierarchical or global_state().config.hierarchical
    if args.zero:
        return {"hierarchical": None, "wire": None}
    inner = resolve_hierarchical(args.hierarchical, hvd.size())
    if not inner:
        return {"hierarchical": {"mode": mode, "inner": 0}, "wire": None}
    comp = resolve_compression(args)
    leaves = wire_leaves(jax.tree_util.tree_leaves(state["params"]), comp)
    plan = plan_buckets(leaves, global_state().config.fusion_threshold)
    wire = hier_wire_summary(plan, hvd.size(), inner, comp)
    log(f"Hierarchical wire split: inner {inner}, ICI {wire['ici_mb']} "
        f"MB, DCN {wire['dcn_mb']} MB @ {wire['dtype']} "
        f"(x{wire['ratio']} vs uncompressed)", file=sys.stderr)
    return {"hierarchical": {"mode": mode, "inner": inner}, "wire": wire}


def overlap_stamp(args, state, log):
    """The overlap/bucket evidence fields for the JSON record: the
    resolved overlap knob plus the fused-bucket plan the gradient
    exchange will execute (count / MB / oversize singletons — the same
    accounting tools/scaling_model.py consumes), so an overlap A/B row
    carries its dispatch-shape evidence like the flash rows carry their
    grid. Uses param shapes only (gradients share them), so it runs
    before the timed windows touch (and donate) the state."""
    import jax

    from horovod_tpu.common.state import global_state
    from horovod_tpu.jax.fusion import plan_buckets, plan_summary

    # Resolve exactly the way fused_reduce will (flag > HOROVOD_OVERLAP
    # config default): the stamp must record what the run executed.
    mode = args.overlap or global_state().config.overlap
    if args.zero:
        # ZeRO's exchange is already reduce-scatter shaped; the overlap
        # knob applies to the fused-psum DP lane only.
        return {"overlap": None, "buckets": None}
    leaves = jax.tree_util.tree_leaves(state["params"])
    summary = plan_summary(plan_buckets(
        leaves, global_state().config.fusion_threshold))
    log(f"Gradient bucket plan: {summary['count']} bucket(s), "
        f"{summary['total_mb']} MB total, "
        f"{summary['oversize_singletons']} oversize singleton(s), "
        f"overlap={mode}", file=sys.stderr)
    return {"overlap": mode, "buckets": summary}


def collectives_stamp(run_step, state, batch, log):
    """The ``"collectives"`` static-audit field: count + bytes of every
    collective in THIS lane's compiled step program, from the hvdverify
    schedule walker (tools/hvdverify — the HVV105 accounting surface,
    cross-checked against the dynamic jaxpr accounting in
    tests/test_wire_bytes.py). Traced on abstract twins of the real
    state/batch BEFORE the timed windows donate the state; pure
    tracing, so it costs seconds of host time and zero device work.
    HVD_BENCH_NO_STATIC_AUDIT=1 skips it (stamps null); a failed audit
    degrades to null rather than killing the measurement."""
    if os.environ.get("HVD_BENCH_NO_STATIC_AUDIT"):
        return {"collectives": None}
    try:
        from tools.hvdverify import abstractify, audit_collectives

        audit = audit_collectives(lambda s, b: run_step(s, b),
                                  abstractify(state), abstractify(batch))
        field = {"count": audit["count"], "bytes": audit["bytes"],
                 "mb": audit["mb"], "by_kind": audit["by_kind"]}
        log(f"Static collective audit: {field['count']} collective(s), "
            f"{field['mb']} MB per step program "
            f"({', '.join(f'{k}:{v}' for k, v in field['by_kind'].items())})",
            file=sys.stderr)
        return {"collectives": field}
    except Exception as exc:  # never fail the measurement for the audit
        log(f"Static collective audit skipped: "
            f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return {"collectives": None}


def resolve_attention(args) -> str:
    """Resolve the LM lane's attention implementation to "dense"|"flash".

    ``--attention auto`` encodes the MEASURED crossover (PERF.md round-5
    honest adjudication #2: dense wins at seq 2048, flash wins from 4096
    and is the only compiling path beyond it) so nobody hand-picks the
    loser at either end; the threshold is
    ops.attention.FLASH_ATTENTION_MIN_SEQ, imported lazily (this helper
    runs in the measuring child — the supervisor parent never imports
    jax). ``--flash-attention`` remains the back-compat spelling of
    ``--attention flash``.
    """
    mode = args.attention
    if args.flash_attention:
        if mode not in (None, "flash"):
            raise ValueError(
                f"--flash-attention conflicts with --attention {mode}")
        mode = "flash"
    if mode is None:
        mode = "dense"
    if mode == "auto":
        from horovod_tpu.ops.attention import FLASH_ATTENTION_MIN_SEQ

        mode = ("flash" if args.seq_len >= FLASH_ATTENTION_MIN_SEQ
                else "dense")
    return mode


def metric_contract(args):
    """(metric, unit) the JSON line will carry — known without a backend,
    so the failure fallback can emit the same contract the success path
    would have. Window lanes (--steps-per-dispatch K > 1) get a _winK
    metric suffix: a different dispatch protocol than the reference's
    per-step headline, recorded alongside it, never over it."""
    if getattr(args, "probe_only", False):
        return "chip_probe_tflops", "TFLOP/s"
    k = getattr(args, "steps_per_dispatch", 1)
    suffix = f"_win{k}" if k > 1 else ""
    if getattr(args, "compile_only", False):
        # Suffixed too: a K-step window's first step compiles a
        # different (scanned) program than the historical 1-step
        # records — same-name rows would compare apples to oranges.
        return f"{args.model}_first_step_secs{suffix}", "secs"
    if args.model == "transformer_lm":
        return (f"transformer_lm_tokens_per_sec_per_chip{suffix}",
                "tokens/sec/chip")
    return f"{args.model}_img_per_sec_per_chip{suffix}", "img/sec/chip"


def supervise(argv, args):
    """Run the measurement in a child process with timeout + retry.

    Returns the process exit code. Prints exactly one JSON line to
    stdout in every outcome (success value, or error fallback).
    """
    import signal
    import subprocess
    import tempfile

    attempts = max(1, int(os.environ.get("HVD_BENCH_ATTEMPTS", "4")))
    # 600s bounds one attempt (a healthy run takes ~2-3 min incl. the
    # first compile) so the worst-case all-attempts-hang stays ~45 min —
    # inside any sane driver window, unlike a 1800s bound.
    timeout = float(os.environ.get("HVD_BENCH_ATTEMPT_TIMEOUT", "600"))
    backoff = float(os.environ.get("HVD_BENCH_BACKOFF", "20"))
    last_err = "unknown"

    # If the DRIVER's own deadline kills us mid-attempt, still honor the
    # one-JSON-line contract on the way out (SIGKILL excepted): without
    # this, an outer timeout reproduces the round-2 empty record.
    current = {"proc": None}

    def _kill_group(proc):
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        try:
            proc.wait(10)
        except subprocess.TimeoutExpired:
            # Uninterruptible (D-state) child: nothing more we can do;
            # the contract line still matters more than the reap.
            pass

    def _disarm():
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.SIG_DFL)

    def _emit_and_exit(signum, frame):
        _disarm()  # a second signal must not print a second line
        proc = current["proc"]
        if proc is not None and proc.poll() is None:
            _kill_group(proc)
        metric_, unit_ = metric_contract(args)
        print(json.dumps({
            "metric": metric_, "value": None, "unit": unit_,
            "vs_baseline": None, "peak": None, "probe_tflops": None,
            "window": getattr(args, "steps_per_dispatch", 1),
            "overlap": getattr(args, "overlap", None),
            "mesh": getattr(args, "mesh", None),
            "hierarchical": None,
            "wire": None,
            "snapshot": None,
            "collectives": None,
            "error": f"supervisor received signal {signum} mid-run "
                     f"(outer/driver deadline?); last state: {last_err}",
        }), flush=True)
        os._exit(0)

    signal.signal(signal.SIGTERM, _emit_and_exit)
    signal.signal(signal.SIGINT, _emit_and_exit)
    for attempt in range(1, attempts + 1):
        with tempfile.NamedTemporaryFile(
                mode="r", suffix=".json", delete=False) as emit:
            emit_path = emit.name
        cmd = [sys.executable, os.path.abspath(__file__), *argv,
               "--_child", "--_emit", emit_path]
        print(f"[bench supervisor] attempt {attempt}/{attempts} "
              f"(timeout {timeout:.0f}s)", file=sys.stderr, flush=True)
        last_err = f"attempt {attempt} in flight"
        try:
            # Child stderr flows through live (the driver log keeps the
            # per-iteration lines); child stdout is discarded — the
            # supervisor alone owns the one-JSON-line stdout contract.
            # Own process group so a timeout (or the signal handler)
            # reaps the measuring child, not just the shell of it. The
            # spawn + handler-visible assignment happens with signals
            # masked so a driver SIGTERM cannot land in between and
            # orphan a child the handler does not know about.
            mask = {signal.SIGTERM, signal.SIGINT}
            signal.pthread_sigmask(signal.SIG_BLOCK, mask)
            try:
                proc = subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL,
                    start_new_session=True)
                current["proc"] = proc
            finally:
                signal.pthread_sigmask(signal.SIG_UNBLOCK, mask)
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            rc = None
            last_err = (f"attempt {attempt} exceeded the "
                        f"{timeout:.0f}s wall-clock timeout "
                        "(hung backend/tunnel)")
            print(f"[bench supervisor] {last_err}", file=sys.stderr,
                  flush=True)
        finally:
            current["proc"] = None
        # A parseable emit file is the success signal, even if the child
        # tripped on a nonzero exit afterwards (e.g. atexit teardown).
        try:
            with open(emit_path) as f:
                payload = json.loads(f.read().strip() or "null")
        except (OSError, ValueError):
            payload = None
        # The child writes its exception summary to <emit>.err — the
        # one way the REASON for a crash survives into this record
        # (stderr flows to the driver log, which sweeps don't keep).
        try:
            with open(emit_path + ".err") as f:
                err_detail = f.read().strip()
        except OSError:
            err_detail = ""
        finally:
            for path in (emit_path, emit_path + ".err"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        if payload is not None:
            _disarm()
            print(json.dumps(payload))
            return 0
        if rc is not None:
            last_err = f"attempt {attempt} exited rc={rc} before emitting"
        if err_detail:
            # Attach the child's exception summary whether it exited or
            # hung afterwards (a crash whose teardown blocks on a dead
            # tunnel is rc=None but the .err was already written).
            last_err += f" [{err_detail[:300]}]"
        if rc is not None or err_detail:
            print(f"[bench supervisor] {last_err}", file=sys.stderr,
                  flush=True)
        if rc in (2, _RC_DETERMINISTIC):
            # argparse usage error or a crash the child classified as
            # deterministic (unknown model etc.): retrying reruns the
            # exact same failure — fail fast instead.
            last_err += " (deterministic failure — not retrying)"
            print("[bench supervisor] not retrying", file=sys.stderr,
                  flush=True)
            break
        if attempt < attempts:
            print(f"[bench supervisor] backing off {backoff:.0f}s",
                  file=sys.stderr, flush=True)
            time.sleep(backoff)
            backoff *= 2
    metric, unit = metric_contract(args)
    _disarm()
    print(json.dumps({
        "metric": metric, "value": None, "unit": unit,
        "vs_baseline": None, "peak": None, "probe_tflops": None,
        "window": getattr(args, "steps_per_dispatch", 1),
        "overlap": getattr(args, "overlap", None),
        "mesh": getattr(args, "mesh", None),
        "hierarchical": None,
        "wire": None,
        "snapshot": None,
        "collectives": None,
        "error": last_err,
    }))
    return 0


def _mesh_config(text):
    """argparse type for --mesh: parse + canonicalize through the
    logical-axis vocabulary (horovod_tpu.parallel.logical), so the
    record always carries the canonical spelling ('tp=4,dp=8' and
    'dp=8,tp=4' stamp identically) and an invalid config is a usage
    error, not a mid-run crash."""
    from horovod_tpu.parallel.logical import (
        format_mesh_config,
        parse_mesh_config,
    )

    try:
        return format_mesh_config(parse_mesh_config(text))
    except Exception as e:
        raise argparse.ArgumentTypeError(str(e))


def build_parser():
    """The bench CLI (exposed so tests/test_sweep_lanes.py can statically
    validate every tools/hw_sweep.py lane's arg wiring — a round-3
    hardware window died to a wiring bug no CPU test had covered)."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--mesh", default=None, type=_mesh_config,
                        help="logical mesh config this lane ran under, "
                             "e.g. 'dp=8,tp=4,sp=2' — canonicalized and "
                             "stamped as the record's \"mesh\" field "
                             "(null when unconfigured)")
    parser.add_argument("--batch-size", type=int, default=None,
                        help="per-chip batch (default: 64 images, or 8 "
                             "sequences for transformer_lm)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=2048,
                        help="context length (transformer_lm)")
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--lm-layers", type=int, default=12)
    parser.add_argument("--lm-dim", type=int, default=768)
    # Alias for --lm-dim (VERDICT r5 ask #4's spelling): the GPT-2-medium
    # MFU lane is `--model transformer_lm --d-model 1024` (+ --lm-layers
    # 24 --lm-heads 16 in tools/hw_sweep.py's transformer_lm_medium
    # lanes). SUPPRESS keeps --lm-dim's default authoritative.
    parser.add_argument("--d-model", dest="lm_dim", type=int,
                        default=argparse.SUPPRESS,
                        help="alias for --lm-dim (transformer_lm model "
                             "width; --d-model 1024 + --lm-layers 24 + "
                             "--lm-heads 16 is the GPT-2-medium config)")
    parser.add_argument("--lm-heads", type=int, default=12)
    parser.add_argument("--steps-per-dispatch", type=int, default=1,
                        help="compile K training steps into ONE XLA "
                             "program (lax.scan window over a device-"
                             "staged K-batch stack): one host dispatch "
                             "and one sync per window amortizes the "
                             "measured 27-32%% per-step host gap on "
                             "short-step models (PERF.md round 5). "
                             "Default 1 preserves the reference "
                             "protocol; window records carry a _winK "
                             "metric suffix and vs_baseline=null")
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--fp32", action="store_true",
                        help="disable bfloat16 compute")
    parser.add_argument("--zero", action="store_true",
                        help="ZeRO-1 optimizer-state sharding over the mesh")
    parser.add_argument("--overlap", default=None,
                        choices=("auto", "on", "off"),
                        help="backward-overlapped bucketed gradient "
                             "collectives (horovod_tpu/jax/fusion.py): "
                             "per-bucket reductions issued in reverse "
                             "bucket order, start-all/unpack-later, "
                             "rs+ag form for big buckets — dispatch "
                             "shape only, numerics bit-identical. "
                             "Default: the HOROVOD_OVERLAP env knob "
                             "(auto). The record stamps the mode plus "
                             "the bucket plan (count/MB/oversize)")
    parser.add_argument("--hierarchical", default=None,
                        choices=("auto", "on", "off"),
                        help="hierarchical bucket collectives "
                             "(horovod_tpu/jax/fusion.py): each fused "
                             "bucket runs intra-slice reduce-scatter -> "
                             "inter-slice DCN exchange of the 1/inner "
                             "shard -> intra-slice all-gather. Default: "
                             "the HOROVOD_HIERARCHICAL env knob (auto = "
                             "engage only on a multi-slice/DCN mesh; "
                             "pin the slice size with HOROVOD_"
                             "HIERARCHICAL_INNER_SIZE). The record "
                             "stamps the resolved mode/inner plus the "
                             "per-leg 'wire' byte split")
    parser.add_argument("--compression", default=None,
                        choices=("none", "fp16", "bf16", "int8", "fp8"),
                        help="gradient wire compression "
                             "(horovod_tpu/jax/compression.py): fp16/"
                             "bf16 cast every leg; int8/fp8 quantize "
                             "ONLY the hierarchical DCN leg (per-bucket "
                             "absmax scale + error-feedback residuals "
                             "in optimizer state) and degrade to "
                             "lossless without --hierarchical. The "
                             "record's 'wire' stamp carries the "
                             "ici/dcn byte split and compression ratio")
    parser.add_argument("--snapshot-every", type=int, default=0,
                        help="measure the elastic snapshot overhead at "
                             "this cadence (steps between host-RAM "
                             "snapshots; horovod_tpu.elastic) and stamp "
                             "{'every', 'ms_per_snapshot', "
                             "'overhead_pct'} into the record as "
                             "'snapshot'. 0 (default) = off. The "
                             "elastic default cadence is 100 "
                             "(HOROVOD_SNAPSHOT_EVERY); acceptance "
                             "budget: overhead <= 2%% of step time at "
                             "the default cadence")
    parser.add_argument("--flash-attention", action="store_true",
                        help="transformer_lm: run the Pallas flash "
                             "attention kernel instead of dense "
                             "attention (A/B at the same protocol); "
                             "back-compat spelling of --attention flash")
    parser.add_argument("--attention", default=None,
                        choices=("auto", "dense", "flash"),
                        help="transformer_lm attention policy: auto "
                             "applies the measured crossover (dense "
                             "below seq 4096, flash at/above — PERF.md "
                             "round-5 adjudication); default dense "
                             "preserves the historical lane wiring")
    parser.add_argument("--flash-full-grid", action="store_true",
                        help="transformer_lm + flash: force the FULL "
                             "causal (q-block, k-block) grid (compute-"
                             "skip only) instead of the packed at-or-"
                             "below-diagonal grid — the truncated-vs-"
                             "full A/B lane in tools/hw_sweep.py")
    parser.add_argument("--flash-bwd", default=None,
                        choices=("auto", "scan", "pallas"),
                        help="transformer_lm + flash: pin the backward "
                             "implementation (auto = measured-crossover "
                             "dispatch: scan below Lk 8192, kernel "
                             "split at/above; unset defers to the "
                             "HVD_FLASH_BWD env default). The grid A/B "
                             "lanes pin pallas so truncated-vs-full "
                             "spans the backward kernels at short seq "
                             "too")
    parser.add_argument("--compile-only", action="store_true",
                        help="build + compile the train step (one first "
                             "step, metric <model>_first_step_secs) and "
                             "exit: warms JAX_COMPILATION_CACHE_DIR so a "
                             "big model's measured lane reruns against a "
                             "warm cache (tools/hw_sweep.py *_warm lanes)")
    parser.add_argument("--probe-only", action="store_true",
                        help="emit only the chip-condition probe "
                             "(metric chip_probe_tflops) and exit — a "
                             "~30s structured health check for deciding "
                             "whether a measurement window is worth "
                             "spending")
    parser.add_argument("--fused-ce", action="store_true",
                        help="transformer_lm: chunked fused cross-"
                             "entropy (ops/xent.py) — the [B,L,vocab] "
                             "fp32 logits tensor never materializes")
    parser.add_argument("--scan-layers", action="store_true",
                        help="transformer_lm: compile the layer stack as "
                             "one lax.scan step over weight-stacked params "
                             "— ~flat compile time in depth (the unrolled "
                             "default grows linearly). Measured cost: -11%% "
                             "step rate vs unrolled (lost cross-layer "
                             "fusion), and at the default LM shape it "
                             "needs --remat (scan stacks every layer's "
                             "attention residuals — 19.3 GB on a 16 GB "
                             "chip without it; PERF.md round 5)")
    parser.add_argument("--remat", action="store_true",
                        help="transformer_lm: rematerialize each block on "
                             "the backward pass (activation memory O(1) "
                             "in depth — the long-context default)")
    parser.add_argument("--fused-bn", action="store_true",
                        help="ResNet family: compute BN statistics in the "
                             "1x1-conv matmul epilogue (Pallas kernel, "
                             "ops/conv_bn.py) instead of a separate "
                             "reduction pass — attacks the convert_reduce "
                             "step-time share identified in PERF.md")
    parser.add_argument("--bf16-momentum", action="store_true",
                        help="keep SGD momentum in bfloat16: halves the "
                             "optimizer-state HBM traffic of the update "
                             "(PERF.md), off by default for reference-"
                             "protocol parity")
    # Internal supervisor plumbing (see module docstring): --_child marks
    # a supervised measurement attempt; --_emit is the file it writes the
    # result JSON to so the parent can distinguish success from a hang.
    parser.add_argument("--_child", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--_emit", default="", help=argparse.SUPPRESS)
    return parser


def main():
    args = build_parser().parse_args()

    # Supervision applies only to the single-process driver invocation.
    # Under a multi-process launcher (HOROVOD_RANK set by hvdrun), a
    # per-rank supervisor would retry one rank of an SPMD job — desyncing
    # its peers' collectives — and every non-root rank would report a
    # spurious "never emitted" error. Job-level relaunch there belongs to
    # `hvdrun --restarts`.
    launched_by_hvdrun = os.environ.get("HOROVOD_RANK") is not None
    if (not args._child and not launched_by_hvdrun
            and not os.environ.get("HVD_BENCH_NO_SUPERVISOR")):
        sys.exit(supervise(sys.argv[1:], args))

    try:
        import horovod_tpu.jax as hvd

        hvd.init()
        log = print if hvd.rank() == 0 else (lambda *a, **k: None)

        if args.probe_only:
            probe = probe_chip(log)
            if hvd.rank() == 0:
                line = json.dumps({
                    "metric": "chip_probe_tflops", "value": probe,
                    "unit": "TFLOP/s", "vs_baseline": None,
                    "peak": None, "probe_tflops": probe,
                })
                print(line)
                if args._emit:
                    with open(args._emit, "w") as f:
                        f.write(line + "\n")
            return

        if args.model == "transformer_lm":
            mean, peak, unit, metric, extra = bench_lm(args, log)
        else:
            mean, peak, unit, metric, extra = bench_image(args, log)
        # Probe AFTER the timed windows: adjacent to the measurement it
        # attributes. A process-start probe can be minutes stale by the
        # time compile + warmup finish on a congested tunnel.
        probe = probe_chip(log)
    except Exception as exc:
        # Tell the supervisor whether a retry can help: backend/tunnel
        # flaps are transient; everything else (unknown model, shape
        # errors, OOM — XLA raises RESOURCE_EXHAUSTED with an
        # underscore, and rerunning the same program OOMs the same way)
        # reruns identically.  Leave the exception summary where the
        # supervisor can put it in the error record: a bare "rc=3" cost
        # round 3 a diagnosis (dense seq-4096's failure reason never
        # reached PERF_RUNS.tsv).
        transient_markers = ("backend", "unavailable", "deadline",
                             "tunnel", "connect")
        text = f"{type(exc).__name__}: {exc}"
        if args._emit:
            try:
                with open(args._emit + ".err", "w") as f:
                    f.write(text[:2000])
            except OSError:
                pass
        import traceback

        traceback.print_exc()
        sys.exit(1 if any(m in text.lower() for m in transient_markers)
                 else _RC_DETERMINISTIC)

    if hvd.rank() == 0:
        # vs_baseline is a REFERENCE-PROTOCOL ratio: window lanes
        # (K > 1) change the dispatch protocol, so they carry null
        # rather than an apples-to-oranges comparison.
        base = (None if args.compile_only or args.steps_per_dispatch > 1
                else REFERENCE_BASELINES.get(args.model))
        line = json.dumps({
            "metric": metric,
            "value": round(mean, 2),
            "unit": unit,
            "vs_baseline": round(mean / base, 3) if base else None,
            "peak": round(peak, 2),
            "probe_tflops": probe,
            "window": args.steps_per_dispatch,
            "mesh": args.mesh,
            # LM lanes append the resolved attention implementation and
            # (flash only) the grid/K-V-bytes accounting — the evidence
            # chain for the truncated-vs-full A/B records.
            **extra,
        })
        print(line)
        if args._emit:
            with open(args._emit, "w") as f:
                f.write(line + "\n")


if __name__ == "__main__":
    main()
