#!/usr/bin/env python
"""Canonical scaling benchmark: ResNet-50 synthetic data, Horovod protocol.

Mirrors the reference's benchmark protocol exactly
(reference examples/pytorch_synthetic_benchmark.py:79-110): warmup
iterations, then ``num_iters`` timed groups of ``num_batches_per_iter``
training steps; report images/sec ± CI. TPU-native execution: the whole
step (fwd + bwd + fused gradient allreduce + update) is one XLA program
run over a 1-D "hvd" mesh of every visible chip.

Prints ONE JSON line:
    {"metric": "resnet50_img_per_sec_per_chip", "value": N,
     "unit": "img/sec/chip", "vs_baseline": N}

``vs_baseline`` compares against the reference's published per-GPU
absolute throughput: 1656.82 img/s over 16 Pascal GPUs = 103.55 img/s/GPU
(reference docs/benchmarks.md:22-38) — the only absolute number the
reference publishes.
"""

import argparse
import json
import sys
import time

# The reference publishes exactly one absolute throughput: ResNet-101 at
# 1656.82 img/s over 16 Pascal GPUs (reference docs/benchmarks.md:22-38).
# BASELINE.md calibrates the ResNet-50 north star against the same number
# (ResNet-class, bs=64/device). Other models have no published reference
# throughput, so their JSON carries vs_baseline=null rather than an
# apples-to-oranges ratio.
_REF_PER_DEVICE = 1656.82 / 16.0
REFERENCE_BASELINES = {"resnet50": _REF_PER_DEVICE, "resnet101": _REF_PER_DEVICE}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--batch-size", type=int, default=64, help="per-chip batch size")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--fp32", action="store_true", help="disable bfloat16 compute")
    parser.add_argument("--zero", action="store_true",
                        help="ZeRO-1 optimizer-state sharding over the mesh")
    parser.add_argument("--bf16-momentum", action="store_true",
                        help="keep SGD momentum in bfloat16: halves the "
                             "optimizer-state HBM traffic of the update "
                             "(PERF.md), off by default for reference-"
                             "protocol parity")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hvd
    from horovod_tpu import models

    hvd.init()
    n = hvd.size()

    dtype = jnp.float32 if args.fp32 else jnp.bfloat16
    model = models.build(args.model, num_classes=1000, dtype=dtype)
    rng = jax.random.PRNGKey(42)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    sgd = optax.sgd(
        0.01, momentum=0.9,
        accumulator_dtype=jnp.bfloat16 if args.bf16_momentum else None)
    state, optimizer = models.create_train_state(
        rng, model, sgd, sample, zero=args.zero)
    step_fn = models.make_train_step(model, optimizer, average_loss=False)
    state_spec = models.state_partition_specs(state) if args.zero else P()

    global_batch = args.batch_size * n
    batch = {
        "image": jax.random.normal(rng, (global_batch, args.image_size, args.image_size, 3), jnp.float32),
        "label": jax.random.randint(rng, (global_batch,), 0, 1000),
    }

    # One prebuilt compiled handle — no per-step cache lookup/hashing — with
    # the train state donated so XLA updates weights/momenta in place
    # instead of reallocating ~100 MB every step.
    run_step = hvd.spmd_fn(
        step_fn,
        in_specs=(state_spec, P("hvd")),
        out_specs=(state_spec, P()),
        donate_argnums=(0,),
    )

    log = print if hvd.rank() == 0 else (lambda *a, **k: None)
    log(f"Model: {args.model}, batch size {args.batch_size}/chip, {n} chips "
        f"({jax.devices()[0].platform})", file=sys.stderr)

    # Warmup (compile included, as in the reference's timeit warmup).
    for _ in range(args.num_warmup_batches):
        state, metrics = run_step(state, batch)
    jax.block_until_ready(state)

    img_secs = []
    for x in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, metrics = run_step(state, batch)
        jax.block_until_ready(state)
        elapsed = time.perf_counter() - t0
        img_sec = args.batch_size * args.num_batches_per_iter / elapsed
        log(f"Iter #{x}: {img_sec:.1f} img/sec per chip", file=sys.stderr)
        img_secs.append(img_sec)

    img_sec_mean = float(np.mean(img_secs))
    img_sec_conf = float(1.96 * np.std(img_secs))
    log(f"Img/sec per chip: {img_sec_mean:.1f} +-{img_sec_conf:.1f}", file=sys.stderr)
    log(f"Total img/sec on {n} chip(s): {img_sec_mean * n:.1f} +-{img_sec_conf * n:.1f}",
        file=sys.stderr)

    if hvd.rank() == 0:
        base = REFERENCE_BASELINES.get(args.model)
        print(json.dumps({
            "metric": f"{args.model}_img_per_sec_per_chip",
            "value": round(img_sec_mean, 2),
            "unit": "img/sec/chip",
            "vs_baseline": round(img_sec_mean / base, 3) if base else None,
        }))


if __name__ == "__main__":
    main()
