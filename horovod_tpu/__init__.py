"""horovod_tpu: a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of Horovod 0.15.2 (reference layout:
horovod/{common,tensorflow,torch,mxnet,keras,spark}) designed for TPU
hardware: SPMD over ``jax.sharding.Mesh`` device meshes, XLA collectives on
the ICI instead of MPI/NCCL rings, trace-time tensor fusion instead of a
background coordinator thread, and Pallas kernels for the hot ops.

Bindings:

* ``horovod_tpu.jax``   — flagship (also re-exported at the top level)
* ``horovod_tpu.torch`` — PyTorch CPU binding over the native C++ core
* ``horovod_tpu.tf``    — sessionless TensorFlow binding over the same core
* ``horovod_tpu.flax``  — training-loop callbacks (keras-binding analogue)
* ``horovod_tpu.parallel`` — mesh construction, TP/PP/SP/EP sharding,
  ring attention, sequence parallelism (beyond-reference, TPU-first)
"""

from horovod_tpu.version import __version__
from horovod_tpu.common import jax_compat as _jax_compat

_jax_compat.install()

from horovod_tpu.jax import *  # noqa: F401,F403 — flagship binding at top level
from horovod_tpu.jax import __all__ as _jax_all

__all__ = ["__version__"] + list(_jax_all)
