"""horovod_tpu.flax — training-loop + callback binding (keras analogue).

Parity surface of the reference's keras bindings (horovod/keras/,
horovod/tensorflow/keras/, shared impl horovod/_keras/, SURVEY §2.7):
``create_distributed_optimizer``, the callback set, and ``load_model``/
``save_model`` with optimizer re-wrapping. Keras's ``model.fit`` becomes a
light :class:`TrainLoop` over flax/optax train state — enough structure for
the callbacks to hook, without hiding the jax step function.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
from flax import serialization

from horovod_tpu.flax.callbacks import (
    BroadcastGlobalVariablesCallback,
    Callback,
    CheckpointCallback,
    ElasticSnapshotCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    get_hyperparam,
    set_hyperparam,
)
from horovod_tpu.flax.checkpoint import CheckpointManager
from horovod_tpu.jax.optimizer import (
    DistributedOptimizer,
    broadcast_parameters,
)


def create_distributed_optimizer(optimizer, name=None, **kwargs):
    """Reference _keras/__init__.py:20-70 parity: wrap a user optimizer so
    gradients are cross-rank averaged. ``name`` accepted for signature
    parity (keras needed it for the dynamic subclass)."""
    del name
    return DistributedOptimizer(optimizer, **kwargs)


class TrainLoop:
    """Callback-driven epoch/batch loop over a jax train step.

    ``step_fn(state, batch) -> (state, metrics)`` is a black box — pass an
    ``hvd.spmd_run``-wrapping closure for multi-chip, or a plain jitted
    step for one chip. ``data_fn(epoch)`` yields the epoch's batches.
    """

    def __init__(self, state, step_fn: Callable, data_fn: Callable,
                 callbacks: Optional[List[Callback]] = None):
        self.state = state
        self.step_fn = step_fn
        self.data_fn = data_fn
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.set_loop(self)
        self.history: List[Dict[str, float]] = []
        self.stop_training = False

    def _dispatch(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            getattr(cb, hook)(*args)

    def fit(self, epochs: int) -> List[Dict[str, float]]:
        self._dispatch("on_train_begin", None)
        for epoch in range(epochs):
            if self.stop_training:
                break
            self._dispatch("on_epoch_begin", epoch, None)
            logs: Dict[str, Any] = {}
            count = 0
            for batch_idx, batch in enumerate(self.data_fn(epoch)):
                self._dispatch("on_batch_begin", batch_idx, None)
                self.state, metrics = self.step_fn(self.state, batch)
                batch_logs = {k: v for k, v in (metrics or {}).items()}
                self._dispatch("on_batch_end", batch_idx, batch_logs)
                # Accumulate device values as-is: float() here would force
                # a host sync per batch and defeat jax async dispatch.
                for k, v in batch_logs.items():
                    logs[k] = logs.get(k, 0.0) + v
                count += 1
            epoch_logs = {k: float(v) / max(count, 1)
                          for k, v in logs.items()}
            self._dispatch("on_epoch_end", epoch, epoch_logs)
            self.history.append(epoch_logs)
        self._dispatch("on_train_end", None)
        return self.history


# ------------------------------------------------------------- checkpointing
# Reference pattern (SURVEY §5 checkpoint/resume): save on rank 0 only,
# restore everywhere, then re-broadcast from root.


def _plain_containers(obj):
    """Flax serialization dispatches on exact container type; normalize
    Mapping subclasses (TrainState, FrozenDict) to plain dicts so they
    round-trip. Namedtuples (optax states) are handled natively."""
    from collections.abc import Mapping

    if isinstance(obj, Mapping):
        return {k: _plain_containers(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*(_plain_containers(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_plain_containers(v) for v in obj)
    return obj


def save_model(path: str, state, only_rank0: bool = True) -> None:
    """Serialize a train-state pytree (flax msgpack). With
    ``only_rank0=True`` non-root processes no-op, the reference's
    checkpoint discipline (reference README.md:113-115)."""
    from horovod_tpu.common import basics

    if only_rank0 and basics.is_initialized() and basics.rank() != 0:
        return
    tmp = f"{path}.{os.getpid()}.tmp"
    payload = serialization.to_bytes(_plain_containers(state))
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)


def load_model(path: str, template, root_rank: int = 0,
               broadcast: bool = True):
    """Restore a train-state pytree saved by :func:`save_model`.

    ``template`` supplies the pytree structure (an initialized state).
    With ``broadcast=True`` the restored state is re-broadcast from
    ``root_rank``, mirroring ``hvd.load_model``'s re-wrapping + broadcast
    flow (reference _keras/__init__.py:93-109, keras/__init__.py:121-148).
    """
    from horovod_tpu.common import basics

    # Root-rank-only read (reference restore flow): with broadcast on,
    # non-root ranks take values purely from the broadcast — required on
    # multi-host where only rank 0's filesystem has the checkpoint.
    must_read = (not broadcast or not basics.is_initialized()
                 or basics.rank() == root_rank)
    if must_read:
        with open(path, "rb") as f:
            restored = serialization.from_bytes(_plain_containers(template),
                                                f.read())
    else:
        restored = _plain_containers(template)
    # Rebuild with the template's own container types (TrainState etc.).
    state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template),
        jax.tree_util.tree_leaves(restored))
    if broadcast:
        state = broadcast_parameters(state, root_rank)
    return state


__all__ = [
    "Callback",
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
    "TrainLoop",
    "create_distributed_optimizer",
    "DistributedOptimizer",
    "save_model",
    "load_model",
    "CheckpointManager",
    "CheckpointCallback",
    "ElasticSnapshotCallback",
    "get_hyperparam",
    "set_hyperparam",
]
