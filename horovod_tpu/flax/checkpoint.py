"""Orbax-backed checkpointing — the TPU-native checkpoint/resume path.

The reference delegated checkpointing to frameworks and contributed the
*discipline*: write on rank 0 only, restore then re-broadcast (reference
README.md:113-115, _keras/__init__.py:93-109, torch/__init__.py:232-348).
:func:`horovod_tpu.flax.save_model` / ``load_model`` reproduce exactly
that. This module is the path that discipline cannot reach: on pods the
train state may be *sharded* (ZeRO optimizer vectors, TP weights) and
larger than any single host, so "rank 0 writes everything" stops being
possible. Orbax writes each array shard from the process that owns it,
commits atomically, and restores arrays directly to their target
shardings — no gather, no re-broadcast.

Usage::

    ckpt = hvd_flax.CheckpointManager("/ckpts", max_to_keep=3)
    for epoch in ...:
        ...
        ckpt.save(step, state)            # async; shards written in place
    # resume (all processes):
    step = ckpt.latest_step()
    if step is not None:
        state = ckpt.restore(step, state) # restored WITH its shardings
    ckpt.close()
"""

from __future__ import annotations

import os
from typing import Any, Optional


class CheckpointManager:
    """Thin veneer over ``orbax.checkpoint.CheckpointManager`` wired to
    horovod_tpu semantics: every process participates (required for
    sharded state), saves are atomic, old steps are garbage-collected."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any) -> bool:
        """Save ``state`` (any pytree of arrays, sharded or replicated)
        under ``step``. Returns whether a save was performed (the manager
        may skip per its policy)."""
        return self._mngr.save(
            int(step), args=self._ocp.args.StandardSave(state)
        )

    def restore(self, step: Optional[int] = None, template: Any = None):
        """Restore ``step`` (default: latest). ``template`` — a concrete
        or abstract (ShapeDtypeStruct) pytree — pins structure, dtypes and
        target shardings; sharded leaves come back sharded."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self._mngr.directory}"
                )
        args = (
            self._ocp.args.StandardRestore(template)
            if template is not None
            else self._ocp.args.StandardRestore()
        )
        return self._mngr.restore(int(step), args=args)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def wait_until_finished(self) -> None:
        """Block until outstanding async saves are committed."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
