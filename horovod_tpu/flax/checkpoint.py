"""Checkpointing — orbax-backed when available, pure-numpy otherwise.

The reference delegated checkpointing to frameworks and contributed the
*discipline*: write on rank 0 only, restore then re-broadcast (reference
README.md:113-115, _keras/__init__.py:93-109, torch/__init__.py:232-348).
:func:`horovod_tpu.flax.save_model` / ``load_model`` reproduce exactly
that. This module is the path that discipline cannot reach: on pods the
train state may be *sharded* (ZeRO optimizer vectors, TP weights) and
larger than any single host, so "rank 0 writes everything" stops being
possible. Orbax writes each array shard from the process that owns it,
commits atomically, and restores arrays directly to their target
shardings — no gather, no re-broadcast.

Two backends behind one :class:`CheckpointManager` surface:

* **orbax** (default when importable) — the full pod story: cross-host
  sharded arrays, async commit, the OCDBT formats.
* **numpy** (automatic fallback, or ``backend="numpy"`` /
  ``HVD_CHECKPOINT_BACKEND=numpy``) — a dependency-free per-process
  shard writer with atomic rename-commit, so the elastic disk spill
  (:mod:`horovod_tpu.elastic.snapshot`) and its CI run in environments
  without orbax. It handles every state whose leaves are addressable by
  the writing process (single-host jobs, including locally-sharded ZeRO
  state); cross-host sharded leaves need orbax. Restore requires a
  ``template`` (the structure/dtype/sharding donor).

Usage::

    ckpt = hvd_flax.CheckpointManager("/ckpts", max_to_keep=3)
    for epoch in ...:
        ...
        ckpt.save(step, state)            # async; shards written in place
    # resume (all processes):
    step = ckpt.latest_step()
    if step is not None:
        state = ckpt.restore(step, state) # restored WITH its shardings
    ckpt.close()
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, List, Optional

import numpy as np

BACKENDS = ("auto", "orbax", "numpy")


def _resolve_backend(backend: Optional[str]) -> str:
    choice = (backend
              or os.environ.get("HVD_CHECKPOINT_BACKEND", "").strip().lower()
              or "auto")
    if choice not in BACKENDS:
        raise ValueError(
            f"checkpoint backend {choice!r}: expected one of {BACKENDS}")
    if choice == "numpy":
        return "numpy"
    try:
        import orbax.checkpoint  # noqa: F401

        return "orbax"
    except ImportError:
        if choice == "orbax":
            raise
        return "numpy"


class CheckpointManager:
    """Thin veneer wired to horovod_tpu semantics: every process
    participates (required for sharded state), saves are atomic, old
    steps are garbage-collected. ``backend`` pins the implementation
    (``auto`` | ``orbax`` | ``numpy``; env ``HVD_CHECKPOINT_BACKEND``);
    the :attr:`backend` attribute reports what was resolved."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True, backend: Optional[str] = None):
        self.backend = _resolve_backend(backend)
        impl = (_OrbaxManager if self.backend == "orbax"
                else _NumpyManager)
        self._impl = impl(os.path.abspath(directory),
                          max_to_keep=max_to_keep, async_save=async_save)

    @property
    def directory(self) -> str:
        return self._impl.directory

    def save(self, step: int, state: Any) -> bool:
        """Save ``state`` (any pytree of arrays, sharded or replicated)
        under ``step``. Returns whether a save was performed (the manager
        may skip per its policy)."""
        return self._impl.save(int(step), state)

    def restore(self, step: Optional[int] = None, template: Any = None):
        """Restore ``step`` (default: latest). ``template`` — a concrete
        or abstract (ShapeDtypeStruct) pytree — pins structure, dtypes and
        target shardings; sharded leaves come back sharded. The numpy
        backend requires it."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        return self._impl.restore(int(step), template)

    def latest_step(self) -> Optional[int]:
        return self._impl.latest_step()

    def all_steps(self) -> List[int]:
        return self._impl.all_steps()

    def wait_until_finished(self) -> None:
        """Block until outstanding async saves are committed."""
        self._impl.wait_until_finished()

    def close(self) -> None:
        self._impl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _OrbaxManager:
    """The orbax path (unchanged semantics from the pre-fallback
    manager)."""

    def __init__(self, directory: str, max_to_keep: int,
                 async_save: bool):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any) -> bool:
        return self._mngr.save(
            step, args=self._ocp.args.StandardSave(state))

    def restore(self, step: int, template: Any):
        args = (
            self._ocp.args.StandardRestore(template)
            if template is not None
            else self._ocp.args.StandardRestore()
        )
        return self._mngr.restore(step, args=args)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mngr.all_steps())

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


# ------------------------------------------------------------- numpy shard
# Layout:  <root>/step_<n>/shard-<proc>.bin   raw little-endian leaf bytes
#          <root>/step_<n>/shard-<proc>.json  leaf dtypes/shapes/offsets
#          <root>/step_<n>/COMMIT             commit marker (written last)
# Every file lands via tmp + os.replace; the COMMIT marker (written by
# process 0 once every process's shard json exists) makes the whole step
# atomic — readers ignore uncommitted step dirs.

_COMMIT = "COMMIT"


def _proc_info():
    from horovod_tpu.common import basics

    if basics.is_initialized():
        return basics.process_rank(), basics.process_count()
    return 0, 1


class _NumpyManager:
    """Pure-numpy per-process shard writer with atomic rename-commit."""

    def __init__(self, directory: str, max_to_keep: int,
                 async_save: bool):
        # async_save accepted for API parity; writes are synchronous
        # (the elastic Snapshotter provides the async layer above).
        del async_save
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    # ----------------------------------------------------------- helpers
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def _committed(self, path: str) -> bool:
        return os.path.exists(os.path.join(path, _COMMIT))

    # -------------------------------------------------------------- save
    def save(self, step: int, state: Any) -> bool:
        import jax

        proc, nproc = _proc_info()
        step_dir = self._step_dir(step)
        os.makedirs(step_dir, exist_ok=True)
        leaves, _ = jax.tree_util.tree_flatten(state)
        meta = []
        offset = 0
        bin_tmp = os.path.join(step_dir, f".shard-{proc}.bin.tmp")
        with open(bin_tmp, "wb") as f:
            for i, leaf in enumerate(leaves):
                # np.asarray keeps 0-d shape (ascontiguousarray would
                # promote scalars to (1,)); tobytes C-order-copies any
                # non-contiguous input.
                arr = np.asarray(leaf)
                data = arr.tobytes()
                f.write(data)
                meta.append({"dtype": arr.dtype.name,
                             "shape": list(arr.shape),
                             "offset": offset, "nbytes": len(data)})
                offset += len(data)
        os.replace(bin_tmp, os.path.join(step_dir, f"shard-{proc}.bin"))
        json_tmp = os.path.join(step_dir, f".shard-{proc}.json.tmp")
        with open(json_tmp, "w") as f:
            json.dump({"leaves": meta, "proc": proc, "nproc": nproc}, f)
        # The json landing second marks THIS shard complete (its .bin is
        # already in place); the dir-level COMMIT lands after all shards.
        os.replace(json_tmp, os.path.join(step_dir, f"shard-{proc}.json"))
        if proc == 0:
            self._wait_for_shards(step_dir, nproc)
            tmp = os.path.join(step_dir, f".{_COMMIT}.tmp")
            with open(tmp, "w") as f:
                f.write(f"{nproc}\n")
            os.replace(tmp, os.path.join(step_dir, _COMMIT))
            self._gc()
        return True

    def _wait_for_shards(self, step_dir: str, nproc: int,
                         timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            present = [p for p in range(nproc) if os.path.exists(
                os.path.join(step_dir, f"shard-{p}.json"))]
            if len(present) == nproc:
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"checkpoint commit: only {len(present)}/{nproc} "
                    f"process shards landed in {step_dir} within "
                    f"{timeout:.0f}s — a peer died mid-save; the step "
                    "stays uncommitted (readers will use the previous "
                    "one)")
            time.sleep(0.05)

    def _gc(self) -> None:
        steps = self.all_steps()
        for old in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)

    # ----------------------------------------------------------- restore
    def restore(self, step: int, template: Any):
        import jax

        if template is None:
            raise ValueError(
                "the numpy checkpoint backend needs a template pytree "
                "to restore into (structure/dtype/sharding donor); pass "
                "restore(step, template=state)")
        step_dir = self._step_dir(step)
        if not self._committed(step_dir):
            raise FileNotFoundError(
                f"no committed checkpoint for step {step} under "
                f"{self.directory}")
        proc, _ = _proc_info()
        with open(os.path.join(step_dir, f"shard-{proc}.json")) as f:
            meta = json.load(f)["leaves"]
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        if len(meta) != len(t_leaves):
            raise ValueError(
                f"checkpoint step {step} holds {len(meta)} leaves but "
                f"the template has {len(t_leaves)} — structure changed "
                "since the save")
        with open(os.path.join(step_dir, f"shard-{proc}.bin"), "rb") as f:
            blob = f.read()
        out = []
        for entry, tmpl in zip(meta, t_leaves):
            arr = np.frombuffer(
                blob, dtype=np.dtype(entry["dtype"]),
                count=int(np.prod(entry["shape"], dtype=np.int64))
                if entry["shape"] else 1,
                offset=entry["offset"]).reshape(entry["shape"])
            sharding = getattr(tmpl, "sharding", None)
            if sharding is not None and not isinstance(
                    sharding, jax.sharding.SingleDeviceSharding):
                # Mesh-sharded template leaves come back SHARDED.
                arr = jax.device_put(arr, sharding)
            else:
                # Single-device templates stay host-side/uncommitted:
                # device_put would COMMIT the leaf to that one device
                # and poison any later multi-device dispatch (jit is
                # free to place uncommitted arrays).
                arr = arr.copy()  # frombuffer views are read-only
            out.append(arr)
        return jax.tree_util.tree_unflatten(treedef, out)

    # ------------------------------------------------------- bookkeeping
    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        steps = []
        for n in names:
            if not n.startswith("step_"):
                continue
            try:
                step = int(n[len("step_"):])
            except ValueError:
                continue
            if self._committed(os.path.join(self.directory, n)):
                steps.append(step)
        return sorted(steps)

    def wait_until_finished(self) -> None:
        pass  # synchronous writes: nothing outstanding

    def close(self) -> None:
        pass
