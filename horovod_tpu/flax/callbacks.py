"""Training-loop callbacks: the keras-binding analogue.

Parity surface of reference horovod/_keras/callbacks.py (169 LoC), bound to
flax/optax instead of keras:

* :class:`BroadcastGlobalVariablesCallback` — reference :20-30
* :class:`MetricAverageCallback`            — reference :33-67
* :class:`LearningRateScheduleCallback`     — reference :70-147
* :class:`LearningRateWarmupCallback`       — reference :149-168

Learning-rate mutation requires the inner optimizer to be built with
``optax.inject_hyperparams`` (e.g. ``optax.inject_hyperparams(optax.sgd)(
learning_rate=0.1, momentum=0.9)``) so the LR lives in the optimizer state
as an array — the TPU-native equivalent of keras's mutable ``K.set_value(
opt.lr, ...)``. Momentum correction rescales trace/momentum buffers when
the LR changes, as the reference did for keras SGD.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


class Callback:
    """Hook points mirror keras.callbacks.Callback; each receives the
    :class:`horovod_tpu.flax.TrainLoop` driving training."""

    def set_loop(self, loop) -> None:
        self.loop = loop

    def on_train_begin(self, logs: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_epoch_begin(self, epoch: int,
                       logs: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_batch_begin(self, batch: int,
                       logs: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_batch_end(self, batch: int,
                     logs: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_epoch_end(self, epoch: int,
                     logs: Optional[Dict[str, Any]] = None) -> None:
        pass

    def on_train_end(self, logs: Optional[Dict[str, Any]] = None) -> None:
        pass


# ---------------------------------------------------------------- opt-state
# surgery helpers: locate InjectHyperparamsState / TraceState leaves inside
# an arbitrarily nested optax state tuple (chains, MultiSteps, ...).


def _is_namedtuple(obj) -> bool:
    return isinstance(obj, tuple) and hasattr(obj, "_fields")


def _rewrite_state(node, visit):
    """Depth-first structural rewrite over tuples/namedtuples/lists/dicts.
    ``visit(node)`` may return a replacement (short-circuits recursion into
    that node) or None to recurse."""
    replacement = visit(node)
    if replacement is not None:
        return replacement
    if _is_namedtuple(node):
        return type(node)(*(_rewrite_state(v, visit) for v in node))
    if isinstance(node, tuple):
        return tuple(_rewrite_state(v, visit) for v in node)
    if isinstance(node, list):
        return [_rewrite_state(v, visit) for v in node]
    if isinstance(node, dict):
        return {k: _rewrite_state(v, visit) for k, v in node.items()}
    return node


def get_hyperparam(opt_state, name: str):
    """Read a hyperparameter injected via optax.inject_hyperparams."""
    found = []

    def visit(node):
        if _is_namedtuple(node) and "hyperparams" in getattr(node, "_fields", ()):
            if name in node.hyperparams:
                found.append(node.hyperparams[name])
        return None

    _rewrite_state(opt_state, visit)
    if not found:
        raise KeyError(
            f"hyperparameter {name!r} not found — build the optimizer with "
            "optax.inject_hyperparams so the LR is mutable state")
    return found[0]


def set_hyperparam(opt_state, name: str, value):
    """Return a copy of ``opt_state`` with hyperparameter ``name`` set."""
    hits = []

    def visit(node):
        if _is_namedtuple(node) and "hyperparams" in getattr(node, "_fields", ()):
            if name in node.hyperparams:
                hp = dict(node.hyperparams)
                hp[name] = jnp.asarray(value, jnp.asarray(hp[name]).dtype)
                hits.append(True)
                return node._replace(hyperparams=hp)
        return None

    new_state = _rewrite_state(opt_state, visit)
    if not hits:
        raise KeyError(
            f"hyperparameter {name!r} not found — build the optimizer with "
            "optax.inject_hyperparams so the LR is mutable state")
    return new_state


def scale_momentum(opt_state, factor: float):
    """Multiply momentum/trace buffers by ``factor`` (reference momentum
    correction, _keras/callbacks.py:70-147: when LR jumps by k, old
    momentum is worth k× in the new step-size units)."""

    def visit(node):
        if _is_namedtuple(node) and "trace" in getattr(node, "_fields", ()):
            return node._replace(
                trace=jax.tree_util.tree_map(lambda t: t * factor, node.trace))
        return None

    return _rewrite_state(opt_state, visit)


# ----------------------------------------------------------------- callbacks


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast the full train state from ``root_rank`` at train start
    (reference _keras/callbacks.py:20-30), so all ranks begin from
    identical weights + optimizer state."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, logs=None):
        from horovod_tpu.jax.optimizer import broadcast_parameters

        self.loop.state = broadcast_parameters(self.loop.state,
                                               self.root_rank)


class CheckpointCallback(Callback):
    """Save the train state every ``every_epochs`` epochs (and at train
    end) through an orbax :class:`~horovod_tpu.flax.CheckpointManager`.

    The keras-lane analogue of the reference's ModelCheckpoint-on-rank-0
    recipe (reference examples/keras_imagenet_resnet50.py:66-103) — but
    orbax-backed, so sharded (ZeRO/TP) state saves from every owning
    process and saves are async. ``step_counter`` maps the loop state to
    the checkpoint step id (default: epoch number)."""

    def __init__(self, manager, every_epochs: int = 1, step_counter=None):
        self.manager = manager
        self.every_epochs = max(1, int(every_epochs))
        self.step_counter = step_counter
        self._last_saved: int = -1
        self._last_epoch: int = -1

    def _step_id(self, epoch: int) -> int:
        if self.step_counter is not None:
            return int(self.step_counter(self.loop.state))
        return epoch

    def on_epoch_end(self, epoch, logs=None):
        self._last_epoch = epoch
        if (epoch + 1) % self.every_epochs == 0:
            self._last_saved = self._step_id(epoch)
            self.manager.save(self._last_saved, self.loop.state)

    def on_train_end(self, logs=None):
        # Final state always lands on disk, even when the epoch count is
        # not a multiple of every_epochs.
        if self._last_epoch >= 0:
            final = self._step_id(self._last_epoch)
            if final != self._last_saved:
                self.manager.save(final, self.loop.state)
        self.manager.wait_until_finished()


class ElasticSnapshotCallback(Callback):
    """Elastic-subsystem binding for :class:`TrainLoop`: cadence
    snapshots of ``loop.state`` plus the deferred-preemption epilogue,
    at batch boundaries (the keras-lane face of
    :func:`horovod_tpu.elastic.run_elastic`).

    ``snapshotter``: a :class:`horovod_tpu.elastic.Snapshotter`.
    ``preemption``: a :class:`horovod_tpu.elastic.PreemptionHandler`
    (default: install one on SIGTERM at train begin). ``step_counter``
    maps the loop state to the global step id (default: the state's
    ``"step"`` entry, the :class:`horovod_tpu.models.TrainState`
    layout); snapshots/manifests are labelled with it, so a relaunched
    loop can restore via ``snapshotter.restore(state)`` before ``fit``.
    """

    def __init__(self, snapshotter, preemption=None, step_counter=None,
                 heartbeat=None):
        self.snapshotter = snapshotter
        self.preemption = preemption
        self.step_counter = (step_counter
                             or (lambda state: int(state["step"])))
        self.heartbeat = heartbeat

    def on_train_begin(self, logs=None):
        if self.preemption is None:
            from horovod_tpu.elastic.signals import PreemptionHandler

            self.preemption = PreemptionHandler()
        if self.heartbeat is None:
            # Feed the supervisor's health watchdog when supervised
            # (HOROVOD_HEARTBEAT_DIR exported by hvdrun --elastic);
            # None when unsupervised.
            from horovod_tpu.elastic.signals import Heartbeat

            self.heartbeat = Heartbeat.from_env()
        # No touch here: the first batch includes the jit compile, and
        # a rank only becomes watched once a real boundary passes.

    def on_batch_end(self, batch, logs=None):
        step = self.step_counter(self.loop.state)
        if self.preemption.check():
            # Boundary-time drain + final sync snapshot + exit(75):
            # the deferred half of the flag-only signal handler.
            self.preemption.finalize(self.snapshotter, step,
                                     self.loop.state)
        self.snapshotter.maybe(step, self.loop.state)
        if self.heartbeat is not None:
            self.heartbeat.touch(step)

    def on_train_end(self, logs=None):
        self.snapshotter.flush(self.step_counter(self.loop.state),
                               self.loop.state)
        if self.preemption is not None:
            self.preemption.uninstall()


class MetricAverageCallback(Callback):
    """Average epoch-end metrics over ranks (reference :33-67). Metrics
    produced inside ``spmd_run`` are already chip-averaged; this covers
    process-level metrics (e.g. locally-computed validation scores)."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        from horovod_tpu.jax import mpi_ops

        for key in list(logs):
            val = logs[key]
            if isinstance(val, (int, float, jnp.ndarray)):
                # Per-metric (not per-gradient) reductions: a handful of
                # scalars once per epoch, and each NEEDS its own name on
                # the eager path (timeline identity / negotiation) — not
                # the per-tensor gradient anti-pattern HVD006 targets.
                logs[key] = mpi_ops.allreduce(  # hvdlint: disable=HVD006
                    jnp.asarray(val, jnp.float32), average=True,
                    name=f"metric.{key}")


class LearningRateScheduleCallback(Callback):
    """Schedule LR as ``initial_lr * multiplier(epoch)``
    (reference :70-147).

    ``multiplier`` is a float or a callable of the (possibly fractional)
    epoch. ``staircase=True`` updates on epoch boundaries; otherwise every
    batch with ``epoch + batch/steps_per_epoch``. When the applied LR
    changes and ``momentum_correction`` is set, momentum buffers are
    rescaled by new/old.
    """

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 initial_lr: Optional[float] = None,
                 steps_per_epoch: Optional[int] = None):
        self.multiplier = (multiplier if callable(multiplier)
                           else (lambda epoch: multiplier))
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._last_lr: Optional[float] = None

    def _in_window(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _resolve_initial_lr(self):
        if self.initial_lr is None:
            # First application: adopt the optimizer's current LR
            # (reference read it from the wrapped keras optimizer).
            self.initial_lr = float(
                get_hyperparam(self.loop.state["opt_state"], "learning_rate"))

    def _apply(self, epoch_f) -> None:
        if not self._in_window(epoch_f):
            return
        self._resolve_initial_lr()
        new_lr = self.initial_lr * float(self.multiplier(epoch_f))
        if self._last_lr is not None and math.isclose(self._last_lr, new_lr):
            return
        opt_state = set_hyperparam(self.loop.state["opt_state"],
                                   "learning_rate", new_lr)
        if self.momentum_correction and self._last_lr not in (None, 0.0):
            opt_state = scale_momentum(opt_state, new_lr / self._last_lr)
        self.loop.state["opt_state"] = opt_state
        self._last_lr = new_lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._apply(float(epoch))

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase:
            if self.steps_per_epoch is None:
                raise ValueError(
                    "staircase=False requires steps_per_epoch")
            self._apply(self.current_epoch + batch / self.steps_per_epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual "lr x size" warmup over the first epochs (reference
    :149-168, after Goyal et al. 2017): with base LR already scaled by
    ``size``, ramp the multiplier from 1/size to 1 so training starts at
    the single-rank LR and reaches the scaled LR after ``warmup_epochs``.
    """

    def __init__(self, warmup_epochs: float = 5.0,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        from horovod_tpu.common import basics

        self.verbose = verbose
        size = basics.size() if basics.is_initialized() else 1

        def multiplier(epoch):
            if warmup_epochs <= 0:
                return 1.0
            progress = min(epoch / warmup_epochs, 1.0)
            return (1.0 + progress * (size - 1)) / size

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=None, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.warmup_epochs = warmup_epochs

    def on_batch_begin(self, batch, logs=None):
        if self.current_epoch < self.warmup_epochs:
            super().on_batch_begin(batch, logs)

    def on_epoch_begin(self, epoch, logs=None):
        super().on_epoch_begin(epoch, logs)
        if epoch >= self.warmup_epochs:
            # Warmup over: snap to the full (clamped multiplier = 1) LR so
            # the ramp ends exactly at the scaled rate.
            self._apply(float(epoch))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose and epoch < self.warmup_epochs and self._last_lr:
            print(f"Epoch {epoch + 1}: warmup lr = {self._last_lr:.6f}")
