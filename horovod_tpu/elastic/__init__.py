"""horovod_tpu.elastic — preemption-tolerant training.

The subsystem upstream Horovod grew right after the reference's 0.15
era (Elastic Horovod, v0.20), rebuilt TPU-native: a single preempted
worker or reclaimed TPU must cost at most one snapshot cadence of
recomputation, never the run.

Pieces (each its own module, composable a la carte):

* :mod:`~horovod_tpu.elastic.snapshot` — double-buffered host-RAM
  snapshots every K steps (async d2h), spilled through the
  :class:`~horovod_tpu.flax.CheckpointManager` on a slower cadence with
  an atomic **resume manifest** (step, RNG key, data-shard cursor);
* :mod:`~horovod_tpu.elastic.signals` — SIGTERM/preemption hook:
  flag-only handler, drain + final sync snapshot at the next step
  boundary, exit with the distinct ``EXIT_PREEMPTED`` (75) status;
* :mod:`~horovod_tpu.elastic.supervisor` — the
  ``hvdrun --elastic --max-restarts N`` relaunch policy over the
  launcher's per-rank exit classification;
* :mod:`~horovod_tpu.elastic.faults` — ``HOROVOD_FAULT_PLAN``
  deterministic fault injection (kill/preempt/stall/exit per rank per
  step), so every recovery path runs in CI on CPU;
* :mod:`~horovod_tpu.elastic.loop` — :func:`run_elastic`, the loop that
  wires all of it around any ``(state, batch) -> (state, metrics)``
  step function (plain or ``lax.scan``-windowed).

Quick start::

    ckpt = hvd_flax.CheckpointManager("/ckpts")
    state, metrics, resumed = elastic.run_elastic(
        train_step, state, source.batch_at, num_steps=10_000,
        manager=ckpt, snapshot_every=100, spill_every=5)

launched as::

    hvdrun --elastic --max-restarts 3 -np 8 python train.py

docs/elastic.md has the cadence math, manifest format, fault-plan
grammar and the preemption runbook.
"""

from horovod_tpu.elastic.faults import (FaultAction, FaultInjector,
                                        FaultPlanError, parse_fault_plan,
                                        resize_requests)
from horovod_tpu.elastic.loop import ShardedBatchSource, run_elastic
from horovod_tpu.elastic.signals import (EXIT_PREEMPTED, Heartbeat,
                                         PreemptionHandler)
from horovod_tpu.elastic.snapshot import (ResumeManifest, Snapshotter,
                                          latest_manifest, manifest_steps,
                                          read_manifest, write_manifest)
from horovod_tpu.elastic.supervisor import (HealthWatchdog,
                                            slots_file_capacity, supervise)
from horovod_tpu.run.driver import (EXIT_CLEAN, EXIT_RESIZED, EXIT_USAGE,
                                    WorkerExit, classify_exit)

__all__ = [
    "run_elastic",
    "ShardedBatchSource",
    "Snapshotter",
    "ResumeManifest",
    "write_manifest",
    "read_manifest",
    "latest_manifest",
    "manifest_steps",
    "PreemptionHandler",
    "Heartbeat",
    "HealthWatchdog",
    "FaultInjector",
    "FaultAction",
    "FaultPlanError",
    "parse_fault_plan",
    "resize_requests",
    "supervise",
    "slots_file_capacity",
    "classify_exit",
    "WorkerExit",
    "EXIT_CLEAN",
    "EXIT_PREEMPTED",
    "EXIT_RESIZED",
    "EXIT_USAGE",
]
