"""Supervised elastic relaunch: `hvdrun --elastic --max-restarts N`.

The launcher's fail-fast kill-all (reference MPI semantics) is the
right *teardown*; this module adds the right *recovery*: classify the
incident from the trigger worker's exit code
(:func:`horovod_tpu.run.driver.classify_exit`), tear the world down,
and relaunch. Workers find the latest resume manifest on disk
(:mod:`horovod_tpu.elastic.snapshot`) and continue from the last
committed snapshot — so a preempted or crashed rank costs at most one
snapshot cadence of recomputation, not the run.

Per-incident policy:

* ``clean``     -> done, exit 0
* ``usage``     -> exit 2 immediately (deterministic; reruns identically)
* ``preempted`` -> relaunch (does NOT consume the restart budget by
  default: preemptions are the environment's fault and can recur
  arbitrarily often; ``count_preemptions=True`` restores strict
  budgeting). With ``min_np`` below the current world, the relaunch
  SHRINKS to the surviving rank count instead of burning attempts
  retrying a size the fleet can no longer field.
* ``crashed``   -> relaunch at the same size, consuming one restart
* ``stalled``   -> a worker the health watchdog killed for a stale
  heartbeat; relaunch consuming one restart (a hang can be as
  deterministic as a crash)
* ``resized``   -> the worker drained + snapshotted and exited
  ``EXIT_RESIZED`` on purpose (the ``resize:`` fault action); relaunch
  FREE at the size the fault plan requested — both sides parse
  ``HOROVOD_FAULT_PLAN``, so the requested size needs no side channel.

Growth: ``capacity_fn`` (CLI: ``--slots-file``) reports how many
worker slots the fleet can currently field; each relaunch clamps to
``min(capacity, max_np)``, so a shrunken world grows back on a later
restart when capacity returns. Without a capacity probe the supervisor
is shrink-only (it cannot know the fleet healed) plus the explicit
``resize:`` lane.

Health watchdog: workers touch a per-rank heartbeat at every window
boundary (:class:`~horovod_tpu.elastic.signals.Heartbeat`; the
supervisor exports ``HOROVOD_HEARTBEAT_DIR``); the
:class:`HealthWatchdog` rides the launcher's supervision poll and
SIGKILLs any rank silent past ``watchdog_timeout`` — converting the
today-unrecoverable silent stall (``stall:`` faults, wedged
collectives under the default wait-forever
``HOROVOD_NEGOTIATION_TIMEOUT``) into an ordinary classified incident.

Each attempt exports ``HOROVOD_ELASTIC=1`` and
``HOROVOD_ELASTIC_RESTART=<attempt>`` so fault plans
(:mod:`horovod_tpu.elastic.faults`) stay attempt-deterministic and
training code can tell a relaunch from a first launch.

Recovery metrics: every supervised job can append one JSON line
(``metrics_path``, CLI ``--metrics-file``) in the PERF_RUNS.tsv format
— time-to-detect for watchdog kills, time-to-relaunch, restarts by
exit class, the world-size trajectory — rendered by
``tools/perf_summary.py``'s ``elastic`` column.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

from horovod_tpu.run import launch_job
from horovod_tpu.run.driver import EXIT_USAGE, classify_exit


def _log(msg: str) -> None:
    print(f"hvdrun[elastic]: {msg}", file=sys.stderr, flush=True)


class HealthWatchdog:
    """Supervisor-side stale-heartbeat detector.

    Rides :func:`horovod_tpu.run.launch_job`'s supervision poll:
    :meth:`check` stats the per-rank heartbeat files (throttled to
    ``interval`` so the poll loop stays cheap) and returns the ranks
    whose last beat is older than ``timeout``. The launcher SIGKILLs
    those ranks — the only safe recovery for a silently-stalled worker
    (its collectives may be wedged; a graceful SIGTERM would hang in
    the drain) — and marks their :class:`~horovod_tpu.run.driver.
    WorkerExit` *stalled* so policy and metrics see the real class.

    A rank is only watched once its heartbeat file exists: workers
    that are still importing/compiling (or jobs not using the elastic
    loop at all) are never killed for silence. ``timeout`` must exceed
    the slowest window-boundary interval; the default
    (``HOROVOD_WATCHDOG_TIMEOUT``, 300 s) is sized for real training
    windows, and CI shrinks it to seconds. ssh-remote ranks write
    their heartbeat on their own host, so the existence rule leaves
    them unwatched until the directory is shared storage — local
    placements (and the whole CI surface) get the full protection.
    """

    def __init__(self, directory: str, timeout: float,
                 interval: float = 0.5, _now=time.monotonic):
        from horovod_tpu.elastic.signals import Heartbeat

        self.directory = directory
        self.timeout = float(timeout)
        self.interval = float(interval)
        self._now = _now
        self._fmt = Heartbeat.FILE_FMT
        self._last_check = -float("inf")
        #: rank -> observed heartbeat age (secs) at the kill decision.
        self.kills: Dict[int, float] = {}

    def reset(self) -> None:
        """Per-attempt reset (the supervisor also clears the heartbeat
        files themselves so attempt N's silence is never judged by
        attempt N-1's mtimes)."""
        self.kills.clear()
        self._last_check = -float("inf")

    def check(self, ranks: Sequence[int]) -> Dict[int, float]:
        """Stale ranks among ``ranks`` -> heartbeat age. Throttled:
        returns {} between ``interval`` ticks."""
        now = self._now()
        if now - self._last_check < self.interval:
            return {}
        self._last_check = now
        wall = time.time()
        stale = {}
        for rank in ranks:
            if rank in self.kills:
                continue
            path = os.path.join(self.directory,
                                self._fmt.format(rank=rank))
            try:
                age = wall - os.stat(path).st_mtime
            except OSError:
                continue  # no beat yet: not watched
            if age > self.timeout:
                stale[rank] = age
        return stale


def _resolve_watchdog_timeout(value: Optional[float]) -> float:
    from horovod_tpu.common.config import (DEFAULT_WATCHDOG_TIMEOUT_SECS,
                                           _env_float)

    if value is not None:
        return float(value)
    return _env_float("HOROVOD_WATCHDOG_TIMEOUT",
                      DEFAULT_WATCHDOG_TIMEOUT_SECS)


def slots_file_capacity(path: str) -> Callable[[], Optional[int]]:
    """A ``capacity_fn`` reading currently-available worker slots from
    a file (one integer) an external scheduler/agent keeps current —
    the CI-testable stand-in for real host discovery. Missing or
    malformed file -> None (capacity unknown; the supervisor keeps its
    current size)."""

    def capacity() -> Optional[int]:
        try:
            with open(path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    return capacity


def _write_metrics(path: str, lane: str, record: dict) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    line = f"{stamp}\t{lane}\t{json.dumps(record, sort_keys=True)}\n"
    with open(path, "a") as f:
        f.write(line)


def supervise(cmd: Sequence[str], np: int,
              hosts: Optional[str] = None,
              env: Optional[Dict[str, str]] = None,
              jax_distributed: bool = False,
              max_restarts: int = 1,
              restart_delay: float = 0.0,
              count_preemptions: bool = False,
              max_total_attempts: int = 1000,
              min_np: Optional[int] = None,
              max_np: Optional[int] = None,
              capacity_fn: Optional[Callable[[], Optional[int]]] = None,
              watchdog_timeout: Optional[float] = None,
              heartbeat_dir: Optional[str] = None,
              metrics_path: Optional[str] = None,
              metrics_lane: str = "elastic_supervise",
              _launch=launch_job) -> int:
    """Run ``cmd`` elastically; returns the final job exit code.

    ``max_restarts`` bounds crash/stall-triggered relaunches;
    preemptions and resizes relaunch for free unless
    ``count_preemptions`` (with ``max_total_attempts`` as the runaway
    backstop either way). ``min_np``/``max_np`` (default: ``np`` — a
    fixed world, the PR-5 behavior) bound the elastic world;
    ``capacity_fn`` reports available slots for regrowth;
    ``watchdog_timeout`` (0 disables) arms the stale-heartbeat
    watchdog. ``_launch`` is injectable for tests.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    min_np = np if min_np is None else int(min_np)
    max_np = np if max_np is None else int(max_np)
    if not 1 <= min_np <= np <= max_np:
        raise ValueError(
            f"world bounds must satisfy 1 <= min_np ({min_np}) <= np "
            f"({np}) <= max_np ({max_np})")
    base_env = dict(env if env is not None else os.environ)

    from horovod_tpu.elastic.faults import parse_fault_plan, \
        resize_requests

    resize_plan = resize_requests(
        parse_fault_plan(base_env.get("HOROVOD_FAULT_PLAN", "")))
    for a, n in resize_plan.items():
        if not min_np <= n <= max_np:
            raise ValueError(
                f"fault plan resize n={n} (attempt {a}) is outside the "
                f"elastic world bounds [{min_np}, {max_np}]; widen "
                "--min-np/--max-np or fix the plan")

    timeout = _resolve_watchdog_timeout(watchdog_timeout)
    watchdog = None
    if timeout > 0:
        from horovod_tpu.elastic.signals import namespaced_heartbeat_dir

        # Namespaced per supervisor INSTANCE (a unique subdir even when
        # the caller passes a shared base): two supervisors — or a
        # training job and a serving fleet — on one host must never
        # watch each other's hb-<rank> files, where a foreign rank 0's
        # touches would keep a stalled local rank 0 "alive" forever.
        heartbeat_dir = namespaced_heartbeat_dir(heartbeat_dir)
        base_env["HOROVOD_HEARTBEAT_DIR"] = heartbeat_dir
        watchdog = HealthWatchdog(heartbeat_dir, timeout)
    else:
        # Watchdog disabled: drop any INHERITED heartbeat dir so this
        # job's workers don't feed an outer supervisor's watchdog (a
        # stalled outer rank sharing our rank id would look alive).
        base_env.pop("HOROVOD_HEARTBEAT_DIR", None)

    def _clamp(n: int) -> int:
        return max(min_np, min(max_np, n))

    restarts_used = 0
    attempt = 0
    np_cur = np
    world_trajectory = [np_cur]
    restarts_by_class: Dict[str, int] = {}
    detect_secs: List[float] = []
    relaunch_secs: List[float] = []
    t_job = time.monotonic()
    # None until a real outcome: an exception unwinding the loop must
    # not stamp the metrics record as a clean exit.
    final_code: Optional[int] = None
    t_incident: Optional[float] = None
    try:
        while True:
            if watchdog is not None:
                watchdog.reset()
                # Only the hb-* files this module owns: attempt N must
                # not be judged by attempt N-1's mtimes, but a caller-
                # provided directory may hold unrelated files.
                for name in os.listdir(heartbeat_dir):
                    if not name.startswith("hb-"):
                        continue
                    try:
                        os.unlink(os.path.join(heartbeat_dir, name))
                    except OSError:
                        pass
            wenv = dict(base_env)
            wenv["HOROVOD_ELASTIC"] = "1"
            wenv["HOROVOD_ELASTIC_RESTART"] = str(attempt)
            if t_incident is not None:
                # Supervisor-side relaunch turnaround: incident return
                # -> the relaunch is handed to the launcher (policy +
                # heartbeat cleanup + restart_delay).
                relaunch_secs.append(time.monotonic() - t_incident)
                t_incident = None
            result = _launch(cmd, np=np_cur, hosts=hosts, env=wenv,
                             jax_distributed=jax_distributed,
                             watchdog=watchdog)
            category = result.category
            if category == "clean":
                if attempt:
                    _log(f"job completed after {attempt} relaunch(es) "
                         f"(world trajectory {world_trajectory})")
                final_code = 0
                return 0
            if category == "usage":
                # Exit code 2 reruns identically (bad flags, import-time
                # misuse); burning the budget only delays the real error.
                _log(f"{result.describe()} — deterministic usage error, "
                     "not relaunching")
                final_code = EXIT_USAGE
                return EXIT_USAGE
            restarts_by_class[category] = \
                restarts_by_class.get(category, 0) + 1
            detect_secs.extend(result.stalled_ranks.values())
            consumes = category in ("crashed", "stalled") \
                or (count_preemptions and category in ("preempted",
                                                       "resized"))
            budget_left = max_restarts - restarts_used
            if (consumes and budget_left <= 0) \
                    or attempt + 1 >= max_total_attempts:
                _log(f"{result.describe()} — restart budget exhausted "
                     f"({restarts_used}/{max_restarts} used); giving up")
                final_code = result.code
                return result.code
            if consumes:
                restarts_used += 1

            # ---- world-size policy for the next attempt -------------
            t_incident = time.monotonic()
            np_next = np_cur
            if category == "resized":
                requested = resize_plan.get(attempt)
                if requested is None:
                    _log("EXIT_RESIZED with no resize clause armed for "
                         f"attempt {attempt}; keeping world {np_cur}")
                else:
                    np_next = _clamp(requested)
            elif category == "preempted" and min_np < np_cur:
                # Shrink to the SURVIVORS: every rank that exited on
                # its own before the kill-all was reclaimed (a whole
                # lost host shows up as several preempted pre-kill
                # codes in one poll), and none of them are coming
                # back. (Crashes/stalls keep the size — the host is
                # still there, the process was the problem.)
                lost = max(1, sum(
                    1 for c in result.pre_kill_codes.values()
                    if classify_exit(c) == "preempted"))
                np_next = _clamp(np_cur - lost)
            if capacity_fn is not None and category != "resized":
                # Capacity is the fleet's truth: grow back toward
                # max_np when it returns, shrink below the policy size
                # when even that is gone. An explicit resize: request
                # is never second-guessed — it was validated against
                # the bounds at launch.
                available = capacity_fn()
                if available is not None:
                    np_next = _clamp(min(available, max_np))
            attempt += 1
            if np_next != np_cur:
                _log(f"{result.describe()} — resizing world "
                     f"{np_cur} -> {np_next} and relaunching from the "
                     f"latest snapshot (attempt {attempt}; "
                     f"{max_restarts - restarts_used} crash restart(s) "
                     "left)")
                np_cur = np_next
                world_trajectory.append(np_cur)
            else:
                _log(f"{result.describe()} — relaunching all "
                     f"{np_cur} rank(s) from the latest snapshot "
                     f"(attempt {attempt}; "
                     f"{max_restarts - restarts_used} crash restart(s) "
                     "left)")
            if restart_delay > 0:
                # ssh-remote teardown is asynchronous (pty HUP): let it
                # settle before the relaunch contends for devices.
                time.sleep(restart_delay)
    finally:
        if watchdog is not None:
            # The namespaced heartbeat dir is THIS supervise() call's
            # own (unique by construction): remove it, or a long-lived
            # service looping over supervise() accumulates one orphan
            # dir of stale hb-<rank> files per invocation forever.
            import shutil

            shutil.rmtree(heartbeat_dir, ignore_errors=True)
        if metrics_path:
            record = {
                "metric": "elastic_recovery",
                "value": attempt,
                "unit": "relaunches",
                "elastic": {
                    "attempts": attempt + 1,
                    "restarts_by_class": restarts_by_class,
                    "world": world_trajectory,
                    "final_np": np_cur,
                    "min_np": min_np,
                    "max_np": max_np,
                    "detect_s": round(max(detect_secs), 2)
                    if detect_secs else None,
                    "relaunch_s": round(
                        sum(relaunch_secs) / len(relaunch_secs), 3)
                    if relaunch_secs else None,
                    "wall_s": round(time.monotonic() - t_job, 2),
                    "exit_code": final_code,
                },
            }
            try:
                _write_metrics(metrics_path, metrics_lane, record)
            except OSError as e:
                _log(f"could not write recovery metrics to "
                     f"{metrics_path}: {e}")


__all__ = ["supervise", "HealthWatchdog", "slots_file_capacity"]
