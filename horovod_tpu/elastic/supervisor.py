"""Supervised elastic relaunch: `hvdrun --elastic --max-restarts N`.

The launcher's fail-fast kill-all (reference MPI semantics) is the
right *teardown*; this module adds the right *recovery*: classify the
incident from the trigger worker's exit code
(:func:`horovod_tpu.run.driver.classify_exit`), tear the world down,
and relaunch every rank. Workers find the latest resume manifest on
disk (:mod:`horovod_tpu.elastic.snapshot`) and continue from the last
committed snapshot — so a preempted or crashed rank costs at most one
snapshot cadence of recomputation, not the run.

Per-incident policy:

* ``clean``     -> done, exit 0
* ``usage``     -> exit 2 immediately (deterministic; reruns identically)
* ``preempted`` -> relaunch (does NOT consume the restart budget by
  default: preemptions are the environment's fault and can recur
  arbitrarily often; ``count_preemptions=True`` restores strict
  budgeting)
* ``crashed``   -> relaunch, consuming one restart

Each attempt exports ``HOROVOD_ELASTIC=1`` and
``HOROVOD_ELASTIC_RESTART=<attempt>`` so fault plans
(:mod:`horovod_tpu.elastic.faults`) stay attempt-deterministic and
training code can tell a relaunch from a first launch.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, Optional, Sequence

from horovod_tpu.run import launch_job
from horovod_tpu.run.driver import EXIT_USAGE


def _log(msg: str) -> None:
    print(f"hvdrun[elastic]: {msg}", file=sys.stderr, flush=True)


def supervise(cmd: Sequence[str], np: int,
              hosts: Optional[str] = None,
              env: Optional[Dict[str, str]] = None,
              jax_distributed: bool = False,
              max_restarts: int = 1,
              restart_delay: float = 0.0,
              count_preemptions: bool = False,
              max_total_attempts: int = 1000,
              _launch=launch_job) -> int:
    """Run ``cmd`` elastically; returns the final job exit code.

    ``max_restarts`` bounds crash-triggered relaunches; preemptions
    relaunch for free unless ``count_preemptions`` (with
    ``max_total_attempts`` as the runaway backstop either way).
    ``_launch`` is injectable for tests.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    base_env = dict(env if env is not None else os.environ)
    restarts_used = 0
    attempt = 0
    while True:
        wenv = dict(base_env)
        wenv["HOROVOD_ELASTIC"] = "1"
        wenv["HOROVOD_ELASTIC_RESTART"] = str(attempt)
        result = _launch(cmd, np=np, hosts=hosts, env=wenv,
                         jax_distributed=jax_distributed)
        category = result.category
        if category == "clean":
            if attempt:
                _log(f"job completed after {attempt} relaunch(es)")
            return 0
        if category == "usage":
            # Exit code 2 reruns identically (bad flags, import-time
            # misuse); burning the budget only delays the real error.
            _log(f"{result.describe()} — deterministic usage error, "
                 "not relaunching")
            return EXIT_USAGE
        consumes = category == "crashed" or count_preemptions
        budget_left = max_restarts - restarts_used
        if (consumes and budget_left <= 0) \
                or attempt + 1 >= max_total_attempts:
            _log(f"{result.describe()} — restart budget exhausted "
                 f"({restarts_used}/{max_restarts} used); giving up")
            return result.code
        if consumes:
            restarts_used += 1
        attempt += 1
        _log(f"{result.describe()} — relaunching all ranks from the "
             f"latest snapshot (attempt {attempt}; "
             f"{max_restarts - restarts_used} crash restart(s) left)")
        if restart_delay > 0:
            # ssh-remote teardown is asynchronous (pty HUP): let it
            # settle before the relaunch contends for devices.
            time.sleep(restart_delay)
