"""Deterministic fault injection: every recovery path testable on CPU.

A recovery subsystem that is only exercised by real preemptions is an
untested subsystem. ``HOROVOD_FAULT_PLAN`` describes, in one line, which
rank fails, how, and at which step::

    HOROVOD_FAULT_PLAN="kill:rank=1,step=7;stall:rank=2,step=12"

Grammar (semicolon-separated actions)::

    <kind>:key=value[,key=value...]

    kind    kill     | die by SIGKILL (crash: no cleanup, no snapshot —
                     | the OOM-kill / hardware-loss shape)
            preempt  | deliver SIGTERM to self (exercises the
                     | signals.py drain -> snapshot -> EXIT_PREEMPTED path)
            stall    | stop making progress for `secs` (default: forever)
                     | — exercises the bounded-deadline path
                     | (HOROVOD_NEGOTIATION_TIMEOUT -> HorovodTimeoutError)
                     | and the supervisor's heartbeat watchdog
            exit     | plain sys.exit(`code`) (default 1)
            resize   | drain -> final snapshot -> exit EXIT_RESIZED (76);
                     | the elastic supervisor relaunches the world at
                     | `n` ranks (the deterministic shrink/grow lane —
                     | the supervisor reads the same plan, so no side
                     | channel carries the requested size)
    rank    which global rank fires the action (required, except
            resize: defaults to 0, the resume-authority rank)
    step    the training step BOUNDARY at or after which it fires
            (required; window loops hit the first boundary >= step)
    attempt which elastic launch attempt it fires on (default 0: the
            first launch only, so the relaunch survives — the
            supervisor exports HOROVOD_ELASTIC_RESTART)
    secs    stall duration (stall only)
    code    exit code (exit only)
    n       requested world size (resize only; required, >= 1)

The plan is parsed (and validated fail-fast) by the launcher
(``hvdrun --fault-plan``), threaded to workers through the environment,
and consumed at step boundaries by :class:`FaultInjector` —
:func:`horovod_tpu.elastic.loop.run_elastic` calls ``maybe_inject``
before every window dispatch. Each action fires at most once per
process.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
from typing import List, Optional

KINDS = ("kill", "preempt", "stall", "exit", "resize")

_INT_KEYS = ("rank", "step", "attempt", "code", "n")
_FLOAT_KEYS = ("secs",)


class FaultPlanError(ValueError):
    """Malformed HOROVOD_FAULT_PLAN — raised at parse (launcher) time so
    a typo'd plan fails the launch, not silently injects nothing."""


@dataclasses.dataclass
class FaultAction:
    kind: str
    rank: int
    step: int
    attempt: int = 0
    secs: Optional[float] = None   # stall duration; None = forever
    code: int = 1                  # exit code (kind="exit")
    n: Optional[int] = None        # requested world size (kind="resize")

    def __str__(self) -> str:
        extra = ""
        if self.kind == "stall" and self.secs is not None:
            extra = f",secs={self.secs:g}"
        if self.kind == "exit":
            extra = f",code={self.code}"
        if self.kind == "resize":
            extra = f",n={self.n}"
        return (f"{self.kind}:rank={self.rank},step={self.step}"
                f",attempt={self.attempt}{extra}")


def parse_fault_plan(plan: str) -> List[FaultAction]:
    """Parse the ``HOROVOD_FAULT_PLAN`` grammar into actions.

    Empty/whitespace plans parse to ``[]``; anything malformed raises
    :class:`FaultPlanError` naming the offending clause.
    """
    actions: List[FaultAction] = []
    for clause in (plan or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, sep, rest = clause.partition(":")
        kind = kind.strip().lower()
        if not sep or kind not in KINDS:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: expected "
                f"'<kind>:rank=R,step=S[,...]' with kind in {KINDS}")
        kv = {}
        for pair in rest.split(","):
            key, psep, value = pair.partition("=")
            key = key.strip().lower()
            if not psep or (key not in _INT_KEYS
                            and key not in _FLOAT_KEYS):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: bad key/value "
                    f"{pair.strip()!r} (keys: rank, step, attempt, "
                    "secs, code, n)")
            try:
                kv[key] = (float(value) if key in _FLOAT_KEYS
                           else int(value))
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: {key}={value!r} is "
                    "not a number") from None
        if "step" not in kv or ("rank" not in kv and kind != "resize"):
            raise FaultPlanError(
                f"fault plan clause {clause!r}: rank= and step= are "
                "required")
        if kind == "resize":
            # rank defaults to 0: a resize is world-orchestration, and
            # rank 0 (the resume authority) is the natural drainer.
            kv.setdefault("rank", 0)
            if "n" not in kv:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: resize requires n= "
                    "(the world size to relaunch at)")
            if kv["n"] < 1:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: n={kv['n']} — the "
                    "resized world must keep at least one rank")
        elif "n" in kv:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: n= only applies to "
                "resize actions")
        actions.append(FaultAction(
            kind=kind, rank=kv["rank"], step=kv["step"],
            attempt=kv.get("attempt", 0), secs=kv.get("secs"),
            code=kv.get("code", 1), n=kv.get("n")))
    _check_resize_unambiguous(actions)
    return actions


def _check_resize_unambiguous(actions: List[FaultAction]) -> None:
    """At most one resize per attempt: the supervisor maps an
    EXIT_RESIZED incident on attempt A back to THE resize clause armed
    for A — two clauses would make the requested size ambiguous."""
    seen = {}
    for a in actions:
        if a.kind != "resize":
            continue
        if a.attempt in seen:
            raise FaultPlanError(
                f"fault plan: two resize actions on attempt {a.attempt} "
                f"({seen[a.attempt]} and {a}) — the relaunch size would "
                "be ambiguous; scope each resize to its own attempt")
        seen[a.attempt] = a


def resize_requests(actions: List[FaultAction]) -> dict:
    """``{attempt: n}`` for every resize clause — the supervisor-side
    read of the plan (both sides parse HOROVOD_FAULT_PLAN, so the
    requested size needs no worker->supervisor side channel)."""
    return {a.attempt: a.n for a in actions if a.kind == "resize"}


class FaultInjector:
    """Per-process executor of the fault plan.

    Filtered at construction to this rank + this elastic attempt, then
    ``maybe_inject(step)`` fires each matching action exactly once at
    the first step boundary at or past its ``step``. With no plan it is
    a no-op whose fast path is one ``if not self._armed``.
    """

    def __init__(self, actions: Optional[List[FaultAction]] = None,
                 rank: Optional[int] = None,
                 attempt: Optional[int] = None):
        if actions is None:
            actions = parse_fault_plan(
                os.environ.get("HOROVOD_FAULT_PLAN", ""))
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
        if attempt is None:
            attempt = int(os.environ.get("HOROVOD_ELASTIC_RESTART", "0"))
        self.rank = rank
        self.attempt = attempt
        self._armed = sorted(
            (a for a in actions
             if a.rank == rank and a.attempt == attempt),
            key=lambda a: a.step)

    @classmethod
    def from_env(cls) -> "FaultInjector":
        return cls()

    @property
    def pending(self) -> List[FaultAction]:
        return list(self._armed)

    def maybe_inject(self, step: int, preemption=None) -> None:
        """Fire every armed action whose step boundary has been reached.

        ``preemption``: an optional
        :class:`horovod_tpu.elastic.signals.PreemptionHandler`; when
        given, ``preempt`` and ``resize`` actions trigger it directly
        (deterministic, no signal-delivery race) instead of signalling
        the process — resize with the EXIT_RESIZED status, so the
        boundary drain + final snapshot happen before the exit.
        """
        if not self._armed:
            return
        while self._armed and self._armed[0].step <= step:
            action = self._armed.pop(0)
            self._fire(action, preemption)

    def _fire(self, action: FaultAction, preemption=None) -> None:
        print(f"[hvd elastic] fault injection: {action} firing at "
              f"rank {self.rank} attempt {self.attempt}",
              file=sys.stderr, flush=True)
        if action.kind == "kill":
            # SIGKILL to self: the closest CPU-testable stand-in for an
            # OOM-kill / node loss — no atexit, no snapshot, no flush.
            os.kill(os.getpid(), signal.SIGKILL)
        elif action.kind == "preempt":
            if preemption is not None:
                preemption.trigger()
            else:
                os.kill(os.getpid(), signal.SIGTERM)
        elif action.kind == "stall":
            time.sleep(action.secs if action.secs is not None else 10**9)
        elif action.kind == "exit":
            sys.exit(action.code)
        elif action.kind == "resize":
            # Same deferred discipline as preempt — the loop drains and
            # snapshots at this very boundary before exiting — but with
            # the EXIT_RESIZED status, so the supervisor relaunches at
            # the plan's requested world size instead of the old one.
            from horovod_tpu.run.driver import EXIT_RESIZED

            if preemption is not None:
                preemption.trigger(exit_code=EXIT_RESIZED)
            else:
                sys.exit(EXIT_RESIZED)
