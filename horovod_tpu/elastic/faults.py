"""Deterministic fault injection: every recovery path testable on CPU.

A recovery subsystem that is only exercised by real preemptions is an
untested subsystem. ``HOROVOD_FAULT_PLAN`` describes, in one line, which
rank fails, how, and at which step::

    HOROVOD_FAULT_PLAN="kill:rank=1,step=7;stall:rank=2,step=12"

Two dialects share the clause shape. The TRAINING dialect (below)
addresses ranks at step boundaries; the SERVING dialect
(:func:`parse_serve_fault_plan`) addresses fleet replicas on the wall
clock — ``kill:replica=1,at=2.5s; stall:replica=0,at=4s;
slow:replica=2,at=1s,factor=3`` — because a serving fleet has no shared
step counter, only arrival time (``at`` accepts plain seconds, an
``s`` suffix, or a ``%`` of the workload horizon so CI plans scale with
the bench).

Grammar (semicolon-separated actions)::

    <kind>:key=value[,key=value...]

    kind    kill     | die by SIGKILL (crash: no cleanup, no snapshot —
                     | the OOM-kill / hardware-loss shape)
            preempt  | deliver SIGTERM to self (exercises the
                     | signals.py drain -> snapshot -> EXIT_PREEMPTED path)
            stall    | stop making progress for `secs` (default: forever)
                     | — exercises the bounded-deadline path
                     | (HOROVOD_NEGOTIATION_TIMEOUT -> HorovodTimeoutError)
                     | and the supervisor's heartbeat watchdog
            exit     | plain sys.exit(`code`) (default 1)
            resize   | drain -> final snapshot -> exit EXIT_RESIZED (76);
                     | the elastic supervisor relaunches the world at
                     | `n` ranks (the deterministic shrink/grow lane —
                     | the supervisor reads the same plan, so no side
                     | channel carries the requested size)
    rank    which global rank fires the action (required, except
            resize: defaults to 0, the resume-authority rank)
    step    the training step BOUNDARY at or after which it fires
            (required; window loops hit the first boundary >= step)
    attempt which elastic launch attempt it fires on (default 0: the
            first launch only, so the relaunch survives — the
            supervisor exports HOROVOD_ELASTIC_RESTART)
    secs    stall duration (stall only)
    code    exit code (exit only)
    n       requested world size (resize only; required, >= 1)

The plan is parsed (and validated fail-fast) by the launcher
(``hvdrun --fault-plan``), threaded to workers through the environment,
and consumed at step boundaries by :class:`FaultInjector` —
:func:`horovod_tpu.elastic.loop.run_elastic` calls ``maybe_inject``
before every window dispatch. Each action fires at most once per
process.
"""

from __future__ import annotations

import dataclasses
import math
import os
import signal
import sys
import time
from typing import List, Optional

KINDS = ("kill", "preempt", "stall", "exit", "resize")

_INT_KEYS = ("rank", "step", "attempt", "code", "n")
_FLOAT_KEYS = ("secs",)


class FaultPlanError(ValueError):
    """Malformed HOROVOD_FAULT_PLAN — raised at parse (launcher) time so
    a typo'd plan fails the launch, not silently injects nothing."""


@dataclasses.dataclass
class FaultAction:
    kind: str
    rank: int
    step: int
    attempt: int = 0
    secs: Optional[float] = None   # stall duration; None = forever
    code: int = 1                  # exit code (kind="exit")
    n: Optional[int] = None        # requested world size (kind="resize")

    def __str__(self) -> str:
        extra = ""
        if self.kind == "stall" and self.secs is not None:
            extra = f",secs={self.secs:g}"
        if self.kind == "exit":
            extra = f",code={self.code}"
        if self.kind == "resize":
            extra = f",n={self.n}"
        return (f"{self.kind}:rank={self.rank},step={self.step}"
                f",attempt={self.attempt}{extra}")


def parse_fault_plan(plan: str) -> List[FaultAction]:
    """Parse the ``HOROVOD_FAULT_PLAN`` grammar into actions.

    Empty/whitespace plans parse to ``[]``; anything malformed raises
    :class:`FaultPlanError` naming the offending clause.
    """
    actions: List[FaultAction] = []
    for clause in (plan or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, sep, rest = clause.partition(":")
        kind = kind.strip().lower()
        if not sep or kind not in KINDS:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: expected "
                f"'<kind>:rank=R,step=S[,...]' with kind in {KINDS}")
        kv = {}
        for pair in rest.split(","):
            key, psep, value = pair.partition("=")
            key = key.strip().lower()
            if not psep or (key not in _INT_KEYS
                            and key not in _FLOAT_KEYS):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: bad key/value "
                    f"{pair.strip()!r} (keys: rank, step, attempt, "
                    "secs, code, n)")
            try:
                kv[key] = (float(value) if key in _FLOAT_KEYS
                           else int(value))
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: {key}={value!r} is "
                    "not a number") from None
        if "step" not in kv or ("rank" not in kv and kind != "resize"):
            raise FaultPlanError(
                f"fault plan clause {clause!r}: rank= and step= are "
                "required")
        if kind == "resize":
            # rank defaults to 0: a resize is world-orchestration, and
            # rank 0 (the resume authority) is the natural drainer.
            kv.setdefault("rank", 0)
            if "n" not in kv:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: resize requires n= "
                    "(the world size to relaunch at)")
            if kv["n"] < 1:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: n={kv['n']} — the "
                    "resized world must keep at least one rank")
        elif "n" in kv:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: n= only applies to "
                "resize actions")
        actions.append(FaultAction(
            kind=kind, rank=kv["rank"], step=kv["step"],
            attempt=kv.get("attempt", 0), secs=kv.get("secs"),
            code=kv.get("code", 1), n=kv.get("n")))
    _check_resize_unambiguous(actions)
    return actions


def _check_resize_unambiguous(actions: List[FaultAction]) -> None:
    """At most one resize per attempt: the supervisor maps an
    EXIT_RESIZED incident on attempt A back to THE resize clause armed
    for A — two clauses would make the requested size ambiguous."""
    seen = {}
    for a in actions:
        if a.kind != "resize":
            continue
        if a.attempt in seen:
            raise FaultPlanError(
                f"fault plan: two resize actions on attempt {a.attempt} "
                f"({seen[a.attempt]} and {a}) — the relaunch size would "
                "be ambiguous; scope each resize to its own attempt")
        seen[a.attempt] = a


def resize_requests(actions: List[FaultAction]) -> dict:
    """``{attempt: n}`` for every resize clause — the supervisor-side
    read of the plan (both sides parse HOROVOD_FAULT_PLAN, so the
    requested size needs no worker->supervisor side channel)."""
    return {a.attempt: a.n for a in actions if a.kind == "resize"}


class FaultInjector:
    """Per-process executor of the fault plan.

    Filtered at construction to this rank + this elastic attempt, then
    ``maybe_inject(step)`` fires each matching action exactly once at
    the first step boundary at or past its ``step``. With no plan it is
    a no-op whose fast path is one ``if not self._armed``.
    """

    def __init__(self, actions: Optional[List[FaultAction]] = None,
                 rank: Optional[int] = None,
                 attempt: Optional[int] = None):
        if actions is None:
            actions = parse_fault_plan(
                os.environ.get("HOROVOD_FAULT_PLAN", ""))
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
        if attempt is None:
            attempt = int(os.environ.get("HOROVOD_ELASTIC_RESTART", "0"))
        self.rank = rank
        self.attempt = attempt
        self._armed = sorted(
            (a for a in actions
             if a.rank == rank and a.attempt == attempt),
            key=lambda a: a.step)

    @classmethod
    def from_env(cls) -> "FaultInjector":
        return cls()

    @property
    def pending(self) -> List[FaultAction]:
        return list(self._armed)

    def maybe_inject(self, step: int, preemption=None) -> None:
        """Fire every armed action whose step boundary has been reached.

        ``preemption``: an optional
        :class:`horovod_tpu.elastic.signals.PreemptionHandler`; when
        given, ``preempt`` and ``resize`` actions trigger it directly
        (deterministic, no signal-delivery race) instead of signalling
        the process — resize with the EXIT_RESIZED status, so the
        boundary drain + final snapshot happen before the exit.
        """
        if not self._armed:
            return
        while self._armed and self._armed[0].step <= step:
            action = self._armed.pop(0)
            self._fire(action, preemption)

    def _fire(self, action: FaultAction, preemption=None) -> None:
        print(f"[hvd elastic] fault injection: {action} firing at "
              f"rank {self.rank} attempt {self.attempt}",
              file=sys.stderr, flush=True)
        if action.kind == "kill":
            # SIGKILL to self: the closest CPU-testable stand-in for an
            # OOM-kill / node loss — no atexit, no snapshot, no flush.
            os.kill(os.getpid(), signal.SIGKILL)
        elif action.kind == "preempt":
            if preemption is not None:
                preemption.trigger()
            else:
                os.kill(os.getpid(), signal.SIGTERM)
        elif action.kind == "stall":
            time.sleep(action.secs if action.secs is not None else 10**9)
        elif action.kind == "exit":
            sys.exit(action.code)
        elif action.kind == "resize":
            # Same deferred discipline as preempt — the loop drains and
            # snapshots at this very boundary before exiting — but with
            # the EXIT_RESIZED status, so the supervisor relaunches at
            # the plan's requested world size instead of the old one.
            from horovod_tpu.run.driver import EXIT_RESIZED

            if preemption is not None:
                preemption.trigger(exit_code=EXIT_RESIZED)
            else:
                sys.exit(EXIT_RESIZED)


# --------------------------------------------------------------------------
# The SERVING dialect: replica faults on the wall clock.
#
# A serving fleet (horovod_tpu/serve/fleet.py) has no shared step
# counter to key faults off — replicas step independently and requests
# arrive on the wall clock — so serving clauses address `replica=` and
# fire `at=` a point in time measured from the fleet's first step:
#
#     kill:replica=1,at=2.5s       abrupt replica death (crash shape:
#                                  its engine state is lost wholesale;
#                                  in-flight requests are drained from
#                                  the ROUTER's bookkeeping)
#     stall:replica=0,at=4s        the replica stops stepping (and
#                                  heartbeating) for `secs` (default:
#                                  forever) — the health-watchdog lane
#     slow:replica=2,at=1s,factor=3   every step takes factor x as long
#                                  (degraded-host shape: the router's
#                                  least-loaded policy must steer
#                                  around it, not hang on it)
#
# `at` accepts `2.5`, `2.5s`, or `40%` — the percent form resolves
# against a caller-supplied horizon (tools/serve_bench.py uses the last
# workload arrival) so one CI plan scales with any bench size.
#
# The multi-host fleet (FleetConfig(transport="tcp", hosts=...)) adds
# HOST addressing — a whole machine as the failure domain:
#
#     kill:host=0,at=2.5s          SIGKILL every worker on host 0 (the
#                                  host-OOM / machine-loss shape): all
#                                  its replicas drain + redispatch as
#                                  ONE classified `host_down` incident
#     partition:host=0,at=50%,secs=2   the host's network goes dark for
#                                  `secs` (default: forever) via the
#                                  deterministic injector at the
#                                  transport seam (serve/netfault.py);
#                                  connections from before the window
#                                  come back half-open and reset
#
# kill accepts either replica= or host=; partition is host-only (a NIC
# belongs to a machine); stall/slow stay replica-only (a wedged or slow
# engine is a process property).
#
# The wire-native weight distribution (serve/params_wire.py) adds two
# verbs addressing the params-PUSH lane — the one RPC lane the fleet
# retries (chunk writes are idempotent + digest-verified), so its
# failure modes need their own injectable shapes:
#
#     transfer:replica=0,at=50%      the NEXT params push to the
#                                  replica is torn mid-stream (the
#                                  connection dies after half the
#                                  chunks) — the fleet must classify
#                                  it, back off, reconnect, and RESUME
#                                  from the worker's verified offset
#     corrupt:replica=0,at=50%       the NEXT push delivers one chunk
#                                  whose bytes do not match its own
#                                  crc32 — the worker rejects it with
#                                  a typed ChecksumError and the fleet
#                                  re-sends that chunk (never commits
#                                  a corrupted artifact)
#
# Both are replica-addressed, fire at most once (armed at `at=`,
# consumed by the next push), and need a wire transport (process/tcp)
# — an inproc fleet has no push lane, rejected fail-fast at arm time.

SERVE_KINDS = ("kill", "stall", "slow", "partition", "transfer",
               "corrupt")


@dataclasses.dataclass
class ServeFaultAction:
    kind: str
    replica: Optional[int] = None     # replica-addressed actions
    at: Optional[float] = None        # seconds from fleet start
    at_frac: Optional[float] = None   # fraction of the horizon (at=..%)
    secs: Optional[float] = None      # stall/partition duration; None = forever
    factor: Optional[float] = None    # slow multiplier (kind="slow")
    host: Optional[int] = None        # host-addressed actions (tcp fleet)

    def __str__(self) -> str:
        if self.at_frac is not None:
            at = f"{self.at_frac * 100:g}%"
        elif self.at is not None:
            at = f"{self.at:g}s"
        else:
            at = "?"   # invalid (validate() rejects it) — still printable
        addr = (f"host={self.host}" if self.host is not None
                else f"replica={self.replica}")
        extra = ""
        if self.kind in ("stall", "partition") and self.secs is not None:
            extra = f",secs={self.secs:g}"
        if self.kind == "slow" and self.factor is not None:
            extra = f",factor={self.factor:g}"
        return f"{self.kind}:{addr},at={at}{extra}"

    def validate(self) -> None:
        """Per-action invariants, for actions built in code rather than
        parsed (``ServeFleet.arm_fault_plan`` accepts both): the same
        fail-fast contract the parser enforces, so a malformed action
        raises :class:`FaultPlanError` at ARM time — never a
        ``TypeError`` out of the fleet loop at fire time."""
        if self.kind not in SERVE_KINDS:
            raise FaultPlanError(
                f"fault action {self}: kind must be in {SERVE_KINDS}")
        if self.kind == "partition":
            if self.host is None or self.replica is not None:
                raise FaultPlanError(
                    f"fault action {self}: partition is host-addressed "
                    "(a NIC belongs to a machine) — use host=, not "
                    "replica=")
        elif self.kind == "kill":
            if (self.replica is None) == (self.host is None):
                raise FaultPlanError(
                    f"fault action {self}: kill needs exactly one of "
                    "replica= or host=")
        else:   # stall / slow / transfer / corrupt
            if self.replica is None or self.host is not None:
                raise FaultPlanError(
                    f"fault action {self}: {self.kind} is "
                    "replica-addressed (a wedged/slow engine is a "
                    "process property; a push targets one replica's "
                    "wire) — use replica=, not host=")
        if self.replica is not None and self.replica < 0:
            raise FaultPlanError(
                f"fault action {self}: replica must be >= 0")
        if self.host is not None and self.host < 0:
            raise FaultPlanError(
                f"fault action {self}: host must be >= 0")
        if (self.at is None) == (self.at_frac is None):
            raise FaultPlanError(
                f"fault action {self}: exactly one of at= (seconds) or "
                "at_frac (horizon fraction) must be set")
        if self.at is not None and not (
                self.at >= 0 and math.isfinite(self.at)):
            raise FaultPlanError(
                f"fault action {self}: at must be finite and >= 0")
        if self.at_frac is not None and not 0.0 <= self.at_frac <= 1.0:
            raise FaultPlanError(
                f"fault action {self}: at_frac must be within 0..1")
        if self.kind == "slow":
            if self.factor is None or not (
                    self.factor >= 1.0 and math.isfinite(self.factor)):
                raise FaultPlanError(
                    f"fault action {self}: slow requires a finite "
                    "factor >= 1")
        elif self.factor is not None:
            raise FaultPlanError(
                f"fault action {self}: factor only applies to slow")
        if self.secs is not None:
            if self.kind not in ("stall", "partition"):
                raise FaultPlanError(
                    f"fault action {self}: secs only applies to stall "
                    "and partition")
            if not self.secs > 0 or math.isnan(self.secs):
                raise FaultPlanError(
                    f"fault action {self}: secs must be > 0")

    def resolve_at(self, horizon: Optional[float]) -> float:
        """Absolute fire offset (seconds from fleet start). Percent
        forms need a ``horizon``; a plan using them without one is a
        planning error, raised loudly rather than silently never
        firing."""
        if self.at is not None:
            return self.at
        if horizon is None:
            raise FaultPlanError(
                f"fault action {self} uses a percent at= but no "
                "workload horizon was provided to resolve it against")
        return self.at_frac * horizon


def _parse_at(clause: str, value: str) -> tuple:
    """``at=`` value -> (seconds, fraction) with exactly one set."""
    v = value.strip().lower()
    is_pct = v.endswith("%")
    if is_pct or v.endswith("s"):
        v = v[:-1]
    try:
        num = float(v)
    except ValueError:
        # NOT FaultPlanError's own range errors below — only a
        # non-numeric literal lands here.
        raise FaultPlanError(
            f"fault plan clause {clause!r}: at={value!r} is not a time "
            "(use seconds, '2.5s', or a '40%' horizon fraction)") from None
    if not math.isfinite(num):
        # nan/inf would never fire — and, sorted to the head, would
        # block every later valid action; the contract is fail-fast.
        raise FaultPlanError(
            f"fault plan clause {clause!r}: at={value!r} must be a "
            "finite time")
    if is_pct:
        frac = num / 100.0
        if not 0.0 <= frac <= 1.0:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: at={value!r} must be "
                "within 0%..100% of the horizon")
        return None, frac
    if num < 0:
        raise FaultPlanError(
            f"fault plan clause {clause!r}: at={value!r} must be "
            ">= 0 seconds")
    return num, None


def parse_serve_fault_plan(plan: str) -> List[ServeFaultAction]:
    """Parse the serving fault dialect into actions (sorted by fire
    order is the caller's job — percent and absolute forms can only be
    ordered once the horizon is known). Empty plans parse to ``[]``;
    malformed ones raise :class:`FaultPlanError` naming the clause."""
    actions: List[ServeFaultAction] = []
    for clause in (plan or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, sep, rest = clause.partition(":")
        kind = kind.strip().lower()
        if not sep or kind not in SERVE_KINDS:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: expected "
                f"'<kind>:replica=R,at=T[,...]' (or host=H for "
                f"kill/partition) with kind in {SERVE_KINDS}")
        kv = {}
        for pair in rest.split(","):
            key, psep, value = pair.partition("=")
            key = key.strip().lower()
            if not psep or key not in ("replica", "host", "at", "secs",
                                       "factor"):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: bad key/value "
                    f"{pair.strip()!r} (keys: replica, host, at, secs, "
                    "factor)")
            kv[key] = value.strip()
        if ("replica" not in kv and "host" not in kv) or "at" not in kv:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: replica= and at= are "
                "required (host= replaces replica= on kill/partition "
                "actions)")
        replica = host = None
        if "replica" in kv:
            try:
                replica = int(kv["replica"])
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: "
                    f"replica={kv['replica']!r} is not an integer"
                ) from None
            if replica < 0:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: replica must be >= 0")
        if "host" in kv:
            try:
                host = int(kv["host"])
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: host={kv['host']!r} "
                    "is not an integer") from None
            if host < 0:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: host must be >= 0")
        at, at_frac = _parse_at(clause, kv["at"])
        secs = factor = None
        if "secs" in kv:
            if kind not in ("stall", "partition"):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: secs= only applies "
                    "to stall and partition actions")
            try:
                secs = float(kv["secs"])
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: secs={kv['secs']!r} "
                    "is not a number") from None
            if not secs > 0 or math.isnan(secs):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: secs must be > 0")
        if kind == "slow":
            if "factor" not in kv:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: slow requires "
                    "factor= (the step-time multiplier)")
            try:
                factor = float(kv["factor"])
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: "
                    f"factor={kv['factor']!r} is not a number") from None
            if not (factor >= 1.0 and math.isfinite(factor)):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: factor must be a "
                    "finite number >= 1 (a slow replica takes LONGER "
                    "per step)")
        elif "factor" in kv:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: factor= only applies to "
                "slow actions")
        action = ServeFaultAction(
            kind=kind, replica=replica, at=at, at_frac=at_frac,
            secs=secs, factor=factor, host=host)
        # The addressing-shape invariants (kill: exactly one of
        # replica/host; partition: host only; stall/slow: replica
        # only) live in validate() so hand-built and parsed actions
        # share one fail-fast contract.
        action.validate()
        actions.append(action)
    return actions
