"""Deterministic fault injection: every recovery path testable on CPU.

A recovery subsystem that is only exercised by real preemptions is an
untested subsystem. ``HOROVOD_FAULT_PLAN`` describes, in one line, which
rank fails, how, and at which step::

    HOROVOD_FAULT_PLAN="kill:rank=1,step=7;stall:rank=2,step=12"

Grammar (semicolon-separated actions)::

    <kind>:key=value[,key=value...]

    kind    kill     | die by SIGKILL (crash: no cleanup, no snapshot —
                     | the OOM-kill / hardware-loss shape)
            preempt  | deliver SIGTERM to self (exercises the
                     | signals.py drain -> snapshot -> EXIT_PREEMPTED path)
            stall    | stop making progress for `secs` (default: forever)
                     | — exercises the bounded-deadline path
                     | (HOROVOD_NEGOTIATION_TIMEOUT -> HorovodTimeoutError)
            exit     | plain sys.exit(`code`) (default 1)
    rank    which global rank fires the action (required)
    step    the training step BOUNDARY at or after which it fires
            (required; window loops hit the first boundary >= step)
    attempt which elastic launch attempt it fires on (default 0: the
            first launch only, so the relaunch survives — the
            supervisor exports HOROVOD_ELASTIC_RESTART)
    secs    stall duration (stall only)
    code    exit code (exit only)

The plan is parsed (and validated fail-fast) by the launcher
(``hvdrun --fault-plan``), threaded to workers through the environment,
and consumed at step boundaries by :class:`FaultInjector` —
:func:`horovod_tpu.elastic.loop.run_elastic` calls ``maybe_inject``
before every window dispatch. Each action fires at most once per
process.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
from typing import List, Optional

KINDS = ("kill", "preempt", "stall", "exit")

_INT_KEYS = ("rank", "step", "attempt", "code")
_FLOAT_KEYS = ("secs",)


class FaultPlanError(ValueError):
    """Malformed HOROVOD_FAULT_PLAN — raised at parse (launcher) time so
    a typo'd plan fails the launch, not silently injects nothing."""


@dataclasses.dataclass
class FaultAction:
    kind: str
    rank: int
    step: int
    attempt: int = 0
    secs: Optional[float] = None   # stall duration; None = forever
    code: int = 1                  # exit code (kind="exit")

    def __str__(self) -> str:
        extra = ""
        if self.kind == "stall" and self.secs is not None:
            extra = f",secs={self.secs:g}"
        if self.kind == "exit":
            extra = f",code={self.code}"
        return (f"{self.kind}:rank={self.rank},step={self.step}"
                f",attempt={self.attempt}{extra}")


def parse_fault_plan(plan: str) -> List[FaultAction]:
    """Parse the ``HOROVOD_FAULT_PLAN`` grammar into actions.

    Empty/whitespace plans parse to ``[]``; anything malformed raises
    :class:`FaultPlanError` naming the offending clause.
    """
    actions: List[FaultAction] = []
    for clause in (plan or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        kind, sep, rest = clause.partition(":")
        kind = kind.strip().lower()
        if not sep or kind not in KINDS:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: expected "
                f"'<kind>:rank=R,step=S[,...]' with kind in {KINDS}")
        kv = {}
        for pair in rest.split(","):
            key, psep, value = pair.partition("=")
            key = key.strip().lower()
            if not psep or (key not in _INT_KEYS
                            and key not in _FLOAT_KEYS):
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: bad key/value "
                    f"{pair.strip()!r} (keys: rank, step, attempt, "
                    "secs, code)")
            try:
                kv[key] = (float(value) if key in _FLOAT_KEYS
                           else int(value))
            except ValueError:
                raise FaultPlanError(
                    f"fault plan clause {clause!r}: {key}={value!r} is "
                    "not a number") from None
        if "rank" not in kv or "step" not in kv:
            raise FaultPlanError(
                f"fault plan clause {clause!r}: rank= and step= are "
                "required")
        actions.append(FaultAction(
            kind=kind, rank=kv["rank"], step=kv["step"],
            attempt=kv.get("attempt", 0), secs=kv.get("secs"),
            code=kv.get("code", 1)))
    return actions


class FaultInjector:
    """Per-process executor of the fault plan.

    Filtered at construction to this rank + this elastic attempt, then
    ``maybe_inject(step)`` fires each matching action exactly once at
    the first step boundary at or past its ``step``. With no plan it is
    a no-op whose fast path is one ``if not self._armed``.
    """

    def __init__(self, actions: Optional[List[FaultAction]] = None,
                 rank: Optional[int] = None,
                 attempt: Optional[int] = None):
        if actions is None:
            actions = parse_fault_plan(
                os.environ.get("HOROVOD_FAULT_PLAN", ""))
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
        if attempt is None:
            attempt = int(os.environ.get("HOROVOD_ELASTIC_RESTART", "0"))
        self.rank = rank
        self.attempt = attempt
        self._armed = sorted(
            (a for a in actions
             if a.rank == rank and a.attempt == attempt),
            key=lambda a: a.step)

    @classmethod
    def from_env(cls) -> "FaultInjector":
        return cls()

    @property
    def pending(self) -> List[FaultAction]:
        return list(self._armed)

    def maybe_inject(self, step: int, preemption=None) -> None:
        """Fire every armed action whose step boundary has been reached.

        ``preemption``: an optional
        :class:`horovod_tpu.elastic.signals.PreemptionHandler`; when
        given, ``preempt`` actions trigger it directly (deterministic,
        no signal-delivery race) instead of signalling the process.
        """
        if not self._armed:
            return
        while self._armed and self._armed[0].step <= step:
            action = self._armed.pop(0)
            self._fire(action, preemption)

    def _fire(self, action: FaultAction, preemption=None) -> None:
        print(f"[hvd elastic] fault injection: {action} firing at "
              f"rank {self.rank} attempt {self.attempt}",
              file=sys.stderr, flush=True)
        if action.kind == "kill":
            # SIGKILL to self: the closest CPU-testable stand-in for an
            # OOM-kill / node loss — no atexit, no snapshot, no flush.
            os.kill(os.getpid(), signal.SIGKILL)
        elif action.kind == "preempt":
            if preemption is not None:
                preemption.trigger()
            else:
                os.kill(os.getpid(), signal.SIGTERM)
        elif action.kind == "stall":
            time.sleep(action.secs if action.secs is not None else 10**9)
        elif action.kind == "exit":
            sys.exit(action.code)
