"""The elastic training loop: resume, snapshot, inject, survive.

:func:`run_elastic` wraps any ``(state, batch) -> (state, metrics)``
step function with the full preemption-tolerance stack:

* **resume** — restore the newest manifested snapshot from the
  :class:`~horovod_tpu.flax.CheckpointManager` before the first step
  (bit-exact: weights + opt state + step counter come back as written;
  the data stream re-derives from the step because
  :mod:`horovod_tpu.data.sharding` is deterministic in
  ``(seed, epoch, rank, size)``);
* **snapshot** — a :class:`~horovod_tpu.elastic.snapshot.Snapshotter`
  on a window-aligned cadence (async d2h, disk spill + manifest on the
  slower ``spill_every`` cadence);
* **preemption** — a deferred SIGTERM flag checked at every window
  boundary; on trigger: drain, final sync snapshot, exit
  ``EXIT_PREEMPTED`` (:mod:`horovod_tpu.elastic.signals`);
* **fault injection** — ``HOROVOD_FAULT_PLAN`` actions fire at their
  step boundaries (:mod:`horovod_tpu.elastic.faults`), so every one of
  these paths is CPU-testable;
* **resizing** — a manifest written at a different world size resumes
  through the watermark remap (:meth:`ShardedBatchSource.resume_step`)
  with an ``on_resize`` rescale hook, instead of failing — see
  docs/elastic.md "Resizing the world";
* **liveness** — a per-rank heartbeat touched at every boundary
  (:class:`~horovod_tpu.elastic.signals.Heartbeat`) feeds the
  supervisor's health watchdog, so a silent stall becomes a bounded
  kill+classify+relaunch instead of an eternal hang.

Windows: ``steps_per_dispatch=K`` compiles K steps into one
``lax.scan`` program (:mod:`horovod_tpu.jax.window`); boundaries —
snapshot points, preemption checks, injection points — then fall every
K steps. The train state is NOT donated here: an async snapshot may
still be copying a buffer the next dispatch would otherwise reuse.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from horovod_tpu.elastic.faults import FaultInjector
from horovod_tpu.elastic.signals import Heartbeat, PreemptionHandler
from horovod_tpu.elastic.snapshot import Snapshotter


class ShardedBatchSource:
    """Deterministic, cursor-addressable per-rank batch stream.

    Wraps :func:`horovod_tpu.data.sharding.shard_indices` so that the
    batch for global step ``s`` is a pure function of
    ``(seed, rank, size, s)`` — which is what makes the resume manifest
    one integer instead of an iterator pickle. ``cursor(step)`` reports
    the classic ``{"epoch": e, "offset": o}`` per-rank shard position
    for the manifest.

    **The coverage contract.** Within an epoch, rank ``r``'s step ``s``
    batch occupies positions ``{r + size*(o + j) : j < B}`` of the
    seeded epoch permutation (``o`` = per-rank offset, ``B`` =
    ``batch_size``) — so the union over ranks of one global step is the
    CONTIGUOUS permutation block ``[size*o, size*(o + B))``, and the
    global stream is a prefix of the permutation consumed ``size*B``
    samples per step regardless of how it is cut into ranks. That is
    what makes world resizing well-defined: a resume at a different
    world size continues the same prefix from the same watermark
    (:meth:`resume_step`), dropping nothing and repeating nothing.
    """

    def __init__(self, arrays: dict, batch_size: int,
                 rank: Optional[int] = None, size: Optional[int] = None,
                 shuffle: bool = True, seed: int = 0):
        from horovod_tpu.data.sharding import _resolve

        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"array lengths differ: {lengths}")
        self.arrays = arrays
        self.n = next(iter(lengths.values()))
        self.batch_size = int(batch_size)
        self.rank, self.size = _resolve(rank, size)
        self.shuffle = shuffle
        self.seed = seed
        self.steps_per_epoch = self._steps_per_epoch(self.size)

    def _steps_per_epoch(self, size: int) -> int:
        per_rank = -(-self.n // size)  # ceil: padded shard length
        return max(1, per_rank // self.batch_size)

    @property
    def global_batch_size(self) -> int:
        """Samples the whole world consumes per step (``size * B``)."""
        return self.size * self.batch_size

    def cursor(self, step: int) -> dict:
        return {"epoch": step // self.steps_per_epoch,
                "offset": (step % self.steps_per_epoch) * self.batch_size,
                "rank": self.rank, "size": self.size}

    def indices_at(self, step: int) -> np.ndarray:
        """The dataset indices this rank's ``step`` batch selects."""
        from horovod_tpu.data.sharding import shard_indices

        cur = self.cursor(step)
        idx = shard_indices(self.n, cur["epoch"], self.rank, self.size,
                            self.shuffle, self.seed)
        return idx[cur["offset"]:cur["offset"] + self.batch_size]

    def batch_at(self, step: int) -> dict:
        sel = self.indices_at(step)
        return {k: v[sel] for k, v in self.arrays.items()}

    __call__ = batch_at

    # --------------------------------------------------- resize support

    def consumed_samples(self, step: int) -> int:
        """Global-stream watermark: samples the WORLD has consumed after
        ``step`` completed steps (``step * size * B`` — each epoch
        consumes ``steps_per_epoch`` such blocks). Invariant under
        resizing: :meth:`resume_step` maps a manifest written at another
        world size to the step with the identical watermark."""
        return step * self.global_batch_size

    def global_positions(self, step: int) -> np.ndarray:
        """Absolute global-stream positions this rank's ``step`` batch
        consumes: ``epoch_base + r + size*(o + j)``. The union over
        ranks of one step is a contiguous watermark interval — the
        resize e2e tests assert exactly-once coverage over these."""
        cur = self.cursor(step)
        epoch_base = cur["epoch"] * self.steps_per_epoch \
            * self.global_batch_size
        off = cur["offset"]
        return (epoch_base + self.rank
                + self.size * (off + np.arange(self.batch_size)))

    def resume_step(self, manifest_or_cursor) -> int:
        """Map a cursor written at ANOTHER world size onto this source's
        step counter — the reshard-resume remap.

        The mapping preserves the global-stream watermark: the old
        world consumed ``g = offset * size_old`` samples into epoch
        ``e``; the new world resumes at the step whose watermark is the
        same point. Exactness requires the watermark to sit on a
        new-world global-batch boundary (``size_new * B`` must divide
        ``g``): snapshots land on multiples of the cadence, so choosing
        ``snapshot_every`` such that ``size_new | cadence * size_old``
        (e.g. any cadence for a 2→1 shrink; an even cadence for a 2→4
        grow) makes every snapshot a legal resize point. Off-boundary
        manifests raise rather than silently dropping or repeating the
        fractional batch — the no-drop/no-duplicate contract is strict.
        """
        cur = getattr(manifest_or_cursor, "cursor", manifest_or_cursor)
        if not isinstance(cur, dict) or "offset" not in cur:
            raise ValueError(
                "resume_step needs the manifest's {epoch, offset, size} "
                f"cursor (got {cur!r}); write manifests through a "
                "ShardedBatchSource cursor_fn so resized resumes can "
                "remap the data stream")
        epoch, offset = int(cur["epoch"]), int(cur["offset"])
        old_size = int(cur["size"])
        B = self.batch_size
        g = offset * old_size                  # within-epoch watermark
        if g % self.global_batch_size:
            raise ValueError(
                f"cannot reshard-resume: the manifest's within-epoch "
                f"watermark ({g} samples = offset {offset} x world "
                f"{old_size}) is not a multiple of the new global batch "
                f"({self.size} x {B} = {self.global_batch_size}); "
                "resizing only at snapshot steps where "
                "new_world*batch divides consumed samples keeps the "
                "stream exactly-once (docs/elastic.md)")
        step_in_epoch = g // self.global_batch_size
        # Any cursor past epoch 0 (or landing exactly on an epoch
        # boundary) relies on whole past epochs lining up between the
        # two worlds — if per-epoch sample counts differ, the epochs
        # before this one consumed different prefixes and NO within-
        # epoch offset can make the streams agree.
        epochs_must_match = (epoch > 0
                             or step_in_epoch == self.steps_per_epoch)
        if step_in_epoch > self.steps_per_epoch or (
                epochs_must_match
                and self._epoch_samples(old_size)
                != self._epoch_samples(self.size)):
            raise ValueError(
                f"cannot reshard-resume: epoch {epoch} consumed {g} "
                f"samples at world {old_size} but holds only "
                f"{self._epoch_samples(self.size)} at world {self.size} "
                f"({self.steps_per_epoch} steps x "
                f"{self.global_batch_size}); pad the dataset to a "
                "multiple of lcm(world sizes) x batch so epochs consume "
                "the same sample count at every size (docs/elastic.md)")
        if step_in_epoch == self.steps_per_epoch:
            epoch, step_in_epoch = epoch + 1, 0
        return epoch * self.steps_per_epoch + step_in_epoch

    def _epoch_samples(self, size: int) -> int:
        return self._steps_per_epoch(size) * size * self.batch_size


def _source_of(batch_for_step) -> Optional[ShardedBatchSource]:
    """Recover the ShardedBatchSource behind ``batch_for_step`` when the
    caller passed the source itself or its bound ``batch_at`` — the
    default provider of manifest cursors and the resize remap."""
    if isinstance(batch_for_step, ShardedBatchSource):
        return batch_for_step
    owner = getattr(batch_for_step, "__self__", None)
    return owner if isinstance(owner, ShardedBatchSource) else None


def run_elastic(
    step_fn: Callable,
    state: Any,
    batch_for_step: Callable[[int], Any],
    num_steps: int,
    *,
    manager=None,
    snapshot_every: Optional[int] = None,
    spill_every: int = 1,
    steps_per_dispatch: int = 1,
    rng_key=None,
    snapshotter: Optional[Snapshotter] = None,
    injector: Optional[FaultInjector] = None,
    preemption: Optional[PreemptionHandler] = None,
    cursor_fn: Optional[Callable[[int], Any]] = None,
    on_step: Optional[Callable[[int, Any], None]] = None,
    jit: bool = True,
    final_snapshot: bool = True,
    world_size: Optional[int] = None,
    rank: Optional[int] = None,
    resume_manager=None,
    remap_step: Optional[Callable[[Any], int]] = None,
    on_resize: Optional[Callable[[int, int, Any], Any]] = None,
    heartbeat: Optional[Heartbeat] = None,
) -> Tuple[Any, List[Tuple[int, Any]], int]:
    """Run ``num_steps`` of ``step_fn`` with snapshots and auto-resume.

    ``batch_for_step(step) -> batch`` must be deterministic in the step
    (use :class:`ShardedBatchSource` for real datasets) — that, plus
    the restored state, is the whole bit-exactness argument: replayed
    steps see identical inputs and identical carried state, so the loss
    trajectory after a kill/restore is the fault-free trajectory.

    Returns ``(state, metrics, resumed_from)`` where ``metrics`` is a
    list of ``(completed_steps, window_metrics)`` for the windows this
    invocation actually ran, and ``resumed_from`` the snapshot step the
    run restored (0 = fresh start). ``on_step`` is called with the same
    pair after each window (streaming logs that survive a kill).

    **Resizing.** A manifest written at a different world size is a
    first-class resume, not an error: ``resume_manager`` names the
    authority checkpoint directory every rank restores from (rank 0's,
    per the restore-then-re-broadcast discipline — new ranks of a grown
    world have no history of their own); ``remap_step`` maps the
    manifest onto this world's step counter (defaults to the batch
    source's :meth:`ShardedBatchSource.resume_step` watermark remap);
    ``on_resize(old_world, new_world, state) -> state`` is the
    per-world-change hook — rescale the learning rate / effective batch
    there, mirroring reference Horovod's elastic state callbacks. RNG
    folding stays a pure function of ``(step, rank, world)``, so a
    resized run is reproducible given the same resize schedule.

    ``world_size``/``rank`` default from ``HOROVOD_SIZE``/
    ``HOROVOD_RANK`` and stamp the manifests this loop writes.
    ``heartbeat`` (default: from ``HOROVOD_HEARTBEAT_DIR`` when the
    elastic supervisor set it) is touched at every window boundary so
    the supervisor's health watchdog can tell a slow window from a
    silent stall.
    """
    import os as _os

    import jax

    from horovod_tpu.jax.window import stack_batches, windowed

    if world_size is None:
        world_size = int(_os.environ.get("HOROVOD_SIZE", "1"))
    if rank is None:
        rank = int(_os.environ.get("HOROVOD_RANK", "0"))
    k = max(1, int(steps_per_dispatch))
    if num_steps % k:
        raise ValueError(
            f"num_steps {num_steps} must be a multiple of "
            f"steps_per_dispatch {k}")
    if snapshotter is None:
        snapshotter = Snapshotter(manager, every=snapshot_every,
                                  spill_every=spill_every, rank=rank,
                                  world_size=world_size)
    snapshotter.check_alignment(k)
    if injector is None:
        injector = FaultInjector.from_env()
    own_handler = preemption is None
    if own_handler:
        preemption = PreemptionHandler()
    if heartbeat is None:
        heartbeat = Heartbeat.from_env()
    source = _source_of(batch_for_step)
    if cursor_fn is None:
        cursor_fn = (source.cursor if source is not None
                     else getattr(batch_for_step, "cursor", lambda s: s))
    if remap_step is None and source is not None:
        remap_step = source.resume_step

    # ---- resume -----------------------------------------------------
    # Gate on the SNAPSHOTTER's manager: a caller passing a pre-built
    # Snapshotter(manager=...) must resume too, not just spill.
    # (restore itself returns None when there is no manager anywhere.)
    # With resume_manager given, restore goes through THAT directory —
    # the world's authority snapshot — while spills keep landing in
    # this rank's own manager.
    resumed_from = 0
    restore_snap = snapshotter
    if resume_manager is not None:
        restore_snap = Snapshotter(resume_manager, every=snapshotter.every,
                                   spill_every=snapshotter.spill_every,
                                   rank=rank, world_size=world_size)
    restored = restore_snap.restore(state)
    if restored is not None:
        state, manifest = restored
        if manifest.world_size != world_size:
            if remap_step is None:
                raise ValueError(
                    f"manifest was written at world size "
                    f"{manifest.world_size} but this run has "
                    f"{world_size} ranks; a reshard resume needs a "
                    "remap_step (use a ShardedBatchSource — its "
                    "resume_step remaps the data cursor — or pass "
                    "remap_step= explicitly; docs/elastic.md "
                    "\"Resizing the world\")")
            resumed_from = int(remap_step(manifest))
            print(f"[hvd elastic] reshard resume: manifest step "
                  f"{manifest.step} @ world {manifest.world_size} -> "
                  f"step {resumed_from} @ world {world_size}",
                  file=sys.stderr, flush=True)
            if on_resize is not None:
                resized = on_resize(manifest.world_size, world_size,
                                    state)
                if resized is not None:
                    state = resized
        else:
            resumed_from = manifest.step
        if manifest.rng_key is not None and rng_key is not None:
            rng_key = jax.numpy.asarray(
                manifest.rng(), dtype=np.asarray(rng_key).dtype)
        if resumed_from % k:
            raise ValueError(
                f"resume step {resumed_from} is not a window "
                f"boundary for steps_per_dispatch {k} — the manifest "
                "was written by a loop with a different window size "
                "(or a resize remap landed off-window); rerun with a "
                "compatible steps_per_dispatch")

    window_fn = windowed(step_fn, k)
    if jit:
        window_fn = jax.jit(window_fn)

    def _aux(step):
        aux = {"cursor": cursor_fn(step)}
        if rng_key is not None:
            aux["rng_key"] = rng_key
        return aux

    metrics_out: List[Tuple[int, Any]] = []
    step = resumed_from
    # NOTE: deliberately no heartbeat touch before the first window —
    # the first dispatch includes the XLA compile, which can dwarf any
    # sane watchdog timeout; a rank becomes *watched* only once its
    # first window completes (the Heartbeat/HealthWatchdog existence
    # rule), so compiling is never mistaken for stalling.
    try:
        while step < num_steps:
            injector.maybe_inject(step, preemption=preemption)
            if preemption.check():
                preemption.finalize(snapshotter, step, state,
                                    **_aux(step))
            if k == 1:
                batch = batch_for_step(step)
            else:
                batch = stack_batches(
                    [batch_for_step(s) for s in range(step, step + k)])
            state, metrics = window_fn(state, batch)
            step += k
            snapshotter.maybe(step, state, **_aux(step))
            metrics_out.append((step, metrics))
            if heartbeat is not None:
                heartbeat.touch(step)
            if on_step is not None:
                on_step(step, metrics)
        # One final boundary: a preemption that arrived during the last
        # window still exits preempted (a terminating cluster would
        # otherwise SIGKILL us mid-teardown), and the finished run
        # leaves a complete manifest behind so re-invocation is a no-op
        # resume.
        injector.maybe_inject(step, preemption=preemption)
        if preemption.check():
            preemption.finalize(snapshotter, step, state, **_aux(step))
        if final_snapshot and snapshotter.manager is not None:
            state = jax.block_until_ready(state)
            snapshotter.flush(step, state, **_aux(step))
    finally:
        # A handler this loop installed must not outlive it (finalize's
        # exit path uninstalls on its own before exiting).
        if own_handler:
            preemption.uninstall()
    return state, metrics_out, resumed_from
