"""The elastic training loop: resume, snapshot, inject, survive.

:func:`run_elastic` wraps any ``(state, batch) -> (state, metrics)``
step function with the full preemption-tolerance stack:

* **resume** — restore the newest manifested snapshot from the
  :class:`~horovod_tpu.flax.CheckpointManager` before the first step
  (bit-exact: weights + opt state + step counter come back as written;
  the data stream re-derives from the step because
  :mod:`horovod_tpu.data.sharding` is deterministic in
  ``(seed, epoch, rank, size)``);
* **snapshot** — a :class:`~horovod_tpu.elastic.snapshot.Snapshotter`
  on a window-aligned cadence (async d2h, disk spill + manifest on the
  slower ``spill_every`` cadence);
* **preemption** — a deferred SIGTERM flag checked at every window
  boundary; on trigger: drain, final sync snapshot, exit
  ``EXIT_PREEMPTED`` (:mod:`horovod_tpu.elastic.signals`);
* **fault injection** — ``HOROVOD_FAULT_PLAN`` actions fire at their
  step boundaries (:mod:`horovod_tpu.elastic.faults`), so every one of
  these paths is CPU-testable.

Windows: ``steps_per_dispatch=K`` compiles K steps into one
``lax.scan`` program (:mod:`horovod_tpu.jax.window`); boundaries —
snapshot points, preemption checks, injection points — then fall every
K steps. The train state is NOT donated here: an async snapshot may
still be copying a buffer the next dispatch would otherwise reuse.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from horovod_tpu.elastic.faults import FaultInjector
from horovod_tpu.elastic.signals import PreemptionHandler
from horovod_tpu.elastic.snapshot import Snapshotter


class ShardedBatchSource:
    """Deterministic, cursor-addressable per-rank batch stream.

    Wraps :func:`horovod_tpu.data.sharding.shard_indices` so that the
    batch for global step ``s`` is a pure function of
    ``(seed, rank, size, s)`` — which is what makes the resume manifest
    one integer instead of an iterator pickle. ``cursor(step)`` reports
    the classic ``{"epoch": e, "offset": o}`` per-rank shard position
    for the manifest.
    """

    def __init__(self, arrays: dict, batch_size: int,
                 rank: Optional[int] = None, size: Optional[int] = None,
                 shuffle: bool = True, seed: int = 0):
        from horovod_tpu.data.sharding import _resolve

        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"array lengths differ: {lengths}")
        self.arrays = arrays
        self.n = next(iter(lengths.values()))
        self.batch_size = int(batch_size)
        self.rank, self.size = _resolve(rank, size)
        self.shuffle = shuffle
        self.seed = seed
        per_rank = -(-self.n // self.size)  # ceil: padded shard length
        self.steps_per_epoch = max(1, per_rank // self.batch_size)

    def cursor(self, step: int) -> dict:
        return {"epoch": step // self.steps_per_epoch,
                "offset": (step % self.steps_per_epoch) * self.batch_size,
                "rank": self.rank, "size": self.size}

    def batch_at(self, step: int) -> dict:
        from horovod_tpu.data.sharding import shard_indices

        cur = self.cursor(step)
        idx = shard_indices(self.n, cur["epoch"], self.rank, self.size,
                            self.shuffle, self.seed)
        sel = idx[cur["offset"]:cur["offset"] + self.batch_size]
        return {k: v[sel] for k, v in self.arrays.items()}

    __call__ = batch_at


def run_elastic(
    step_fn: Callable,
    state: Any,
    batch_for_step: Callable[[int], Any],
    num_steps: int,
    *,
    manager=None,
    snapshot_every: Optional[int] = None,
    spill_every: int = 1,
    steps_per_dispatch: int = 1,
    rng_key=None,
    snapshotter: Optional[Snapshotter] = None,
    injector: Optional[FaultInjector] = None,
    preemption: Optional[PreemptionHandler] = None,
    cursor_fn: Optional[Callable[[int], Any]] = None,
    on_step: Optional[Callable[[int, Any], None]] = None,
    jit: bool = True,
    final_snapshot: bool = True,
) -> Tuple[Any, List[Tuple[int, Any]], int]:
    """Run ``num_steps`` of ``step_fn`` with snapshots and auto-resume.

    ``batch_for_step(step) -> batch`` must be deterministic in the step
    (use :class:`ShardedBatchSource` for real datasets) — that, plus
    the restored state, is the whole bit-exactness argument: replayed
    steps see identical inputs and identical carried state, so the loss
    trajectory after a kill/restore is the fault-free trajectory.

    Returns ``(state, metrics, resumed_from)`` where ``metrics`` is a
    list of ``(completed_steps, window_metrics)`` for the windows this
    invocation actually ran, and ``resumed_from`` the snapshot step the
    run restored (0 = fresh start). ``on_step`` is called with the same
    pair after each window (streaming logs that survive a kill).
    """
    import jax

    from horovod_tpu.jax.window import stack_batches, windowed

    k = max(1, int(steps_per_dispatch))
    if num_steps % k:
        raise ValueError(
            f"num_steps {num_steps} must be a multiple of "
            f"steps_per_dispatch {k}")
    if snapshotter is None:
        snapshotter = Snapshotter(manager, every=snapshot_every,
                                  spill_every=spill_every)
    snapshotter.check_alignment(k)
    if injector is None:
        injector = FaultInjector.from_env()
    own_handler = preemption is None
    if own_handler:
        preemption = PreemptionHandler()
    if cursor_fn is None:
        cursor_fn = getattr(batch_for_step, "cursor", lambda s: s)

    # ---- resume -----------------------------------------------------
    # Gate on the SNAPSHOTTER's manager: a caller passing a pre-built
    # Snapshotter(manager=...) must resume too, not just spill.
    # (restore itself returns None when there is no manager anywhere.)
    resumed_from = 0
    restored = snapshotter.restore(state)
    if restored is not None:
        state, manifest = restored
        resumed_from = manifest.step
        if manifest.rng_key is not None and rng_key is not None:
            rng_key = jax.numpy.asarray(
                manifest.rng(), dtype=np.asarray(rng_key).dtype)
        if resumed_from % k:
            raise ValueError(
                f"manifest step {resumed_from} is not a window "
                f"boundary for steps_per_dispatch {k} — it was written "
                "by a loop with a different window size; rerun with "
                "the original steps_per_dispatch")

    window_fn = windowed(step_fn, k)
    if jit:
        window_fn = jax.jit(window_fn)

    def _aux(step):
        aux = {"cursor": cursor_fn(step)}
        if rng_key is not None:
            aux["rng_key"] = rng_key
        return aux

    metrics_out: List[Tuple[int, Any]] = []
    step = resumed_from
    try:
        while step < num_steps:
            injector.maybe_inject(step, preemption=preemption)
            if preemption.check():
                preemption.finalize(snapshotter, step, state,
                                    **_aux(step))
            if k == 1:
                batch = batch_for_step(step)
            else:
                batch = stack_batches(
                    [batch_for_step(s) for s in range(step, step + k)])
            state, metrics = window_fn(state, batch)
            step += k
            snapshotter.maybe(step, state, **_aux(step))
            metrics_out.append((step, metrics))
            if on_step is not None:
                on_step(step, metrics)
        # One final boundary: a preemption that arrived during the last
        # window still exits preempted (a terminating cluster would
        # otherwise SIGKILL us mid-teardown), and the finished run
        # leaves a complete manifest behind so re-invocation is a no-op
        # resume.
        injector.maybe_inject(step, preemption=preemption)
        if preemption.check():
            preemption.finalize(snapshotter, step, state, **_aux(step))
        if final_snapshot and snapshotter.manager is not None:
            state = jax.block_until_ready(state)
            snapshotter.flush(step, state, **_aux(step))
    finally:
        # A handler this loop installed must not outlive it (finalize's
        # exit path uninstalls on its own before exiting).
        if own_handler:
            preemption.uninstall()
    return state, metrics_out, resumed_from
