"""Double-buffered host-RAM train-state snapshots + manifested disk spill.

CheckFreq's observation (Mohan et al., FAST 2021): checkpointing is two
separable costs — getting a consistent copy OUT of the accelerator
(cheap, bounded by d2h bandwidth) and getting it onto durable storage
(slow). So snapshot often, spill rarely:

* every ``every`` steps (window-aligned) the :class:`Snapshotter` starts
  an ASYNC device->host copy of the train state (``copy_to_host_async``
  rides the DMA engines while the next window computes) into one of two
  host buffers — the *pending* buffer; the previous pending snapshot is
  committed (transfer completed) at the NEXT boundary, so the steady
  state overlaps an entire window of compute with each d2h;
* on the ``spill_every``-th snapshot the copy is taken synchronously and
  written through a :class:`horovod_tpu.flax.CheckpointManager` (orbax,
  or its pure-numpy fallback), together with a **resume manifest** —
  step, folded RNG key, data-shard cursor, world size — committed by
  atomic rename, so a relaunch restores bit-exactly;
* a preemption (:mod:`horovod_tpu.elastic.signals`) calls :meth:`flush`:
  one final synchronous snapshot + spill inside the SIGTERM grace
  window.

Cadence math (docs/elastic.md): overhead fraction = d2h_ms / (every *
step_ms); at the default ``every`` = 100 a 100 MB state (~1 ms pinned
d2h) against a 20 ms step costs 0.05% — the acceptance budget is <= 2%.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

MANIFEST_POINTER = "MANIFEST"          # atomic latest-manifest pointer
_MANIFEST_FMT = "manifest-{step}.json"


@dataclasses.dataclass
class ResumeManifest:
    """Everything beyond the weights needed for a bit-exact resume.

    ``step``: completed training steps at the snapshot — the relaunch
    runs steps ``[step, total)``. ``rng_key``: the loop's folded PRNG
    key words (uint32 list; loops that derive per-step keys from the
    carried ``state["step"]`` need nothing here). ``cursor``: the
    per-rank data-shard position (:mod:`horovod_tpu.data.sharding` is
    deterministic in ``(seed, epoch, rank, size)``, so
    ``{"epoch": e, "offset": o}`` pins every rank's stream). ``rank``
    records the writer; ``world_size`` records the world the shards
    were cut for — a resume into a DIFFERENT world size remaps the
    cursor through :meth:`~horovod_tpu.elastic.loop.ShardedBatchSource.
    resume_step` (the reshard path; docs/elastic.md "Resizing the
    world") instead of rejecting the manifest.
    """

    step: int
    world_size: int = 1
    rank: int = 0
    attempt: int = 0
    cursor: Any = None
    rng_key: Optional[List[int]] = None
    wall_time: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ResumeManifest":
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    def rng(self, dtype=np.uint32) -> Optional[np.ndarray]:
        if self.rng_key is None:
            return None
        return np.asarray(self.rng_key, dtype=dtype)


def write_manifest(directory: str, manifest: ResumeManifest) -> str:
    """Commit ``manifest`` under ``directory`` with atomic renames.

    Two-phase: the per-step file lands first (tmp + ``os.replace``),
    then the ``MANIFEST`` pointer flips to it — a crash between the two
    leaves the previous pointer intact, never a torn manifest.
    """
    os.makedirs(directory, exist_ok=True)
    name = _MANIFEST_FMT.format(step=int(manifest.step))
    path = os.path.join(directory, name)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(manifest.to_json() + "\n")
    os.replace(tmp, path)
    pointer = os.path.join(directory, MANIFEST_POINTER)
    tmp = f"{pointer}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(name + "\n")
    os.replace(tmp, pointer)
    return path


def manifest_steps(directory: str) -> List[int]:
    """Steps with a committed manifest file, ascending."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    steps = []
    for n in names:
        if n.startswith("manifest-") and n.endswith(".json"):
            try:
                steps.append(int(n[len("manifest-"):-len(".json")]))
            except ValueError:
                continue
    return sorted(steps)


def read_manifest(directory: str, step: int) -> Optional[ResumeManifest]:
    path = os.path.join(directory, _MANIFEST_FMT.format(step=int(step)))
    try:
        with open(path) as f:
            return ResumeManifest.from_json(f.read())
    except (OSError, ValueError):
        return None


def latest_manifest(directory: str) -> Optional[ResumeManifest]:
    """Newest committed manifest (the ``MANIFEST`` pointer; falls back
    to scanning per-step files if the pointer is missing/torn)."""
    pointer = os.path.join(directory, MANIFEST_POINTER)
    try:
        with open(pointer) as f:
            name = f.read().strip()
        with open(os.path.join(directory, name)) as f:
            return ResumeManifest.from_json(f.read())
    except (OSError, ValueError):
        pass
    steps = manifest_steps(directory)
    return read_manifest(directory, steps[-1]) if steps else None


def _is_jax_array(leaf) -> bool:
    return hasattr(leaf, "copy_to_host_async")


class Snapshotter:
    """Periodic train-state snapshots: async to host RAM, manifested to
    disk on a slower cadence.

    ``manager``: a :class:`horovod_tpu.flax.CheckpointManager` (or any
    object with ``save(step, state)`` / ``directory``); ``None`` keeps
    snapshots in RAM only (bench overhead probes). ``every``: snapshot
    cadence in steps (default: ``HOROVOD_SNAPSHOT_EVERY``);
    ``spill_every``: every how-many-th snapshot also spills to disk
    (1 = all). Window loops must keep ``every`` a multiple of
    ``steps_per_dispatch`` — :meth:`check_alignment` enforces it, since
    a snapshot can only be taken where the host actually holds a
    consistent state, i.e. at window boundaries.
    """

    def __init__(self, manager=None, every: Optional[int] = None,
                 spill_every: int = 1, rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 attempt: Optional[int] = None):
        from horovod_tpu.common.config import DEFAULT_SNAPSHOT_EVERY

        if every is None:
            try:
                every = int(os.environ.get("HOROVOD_SNAPSHOT_EVERY", "")
                            or DEFAULT_SNAPSHOT_EVERY)
            except ValueError:
                every = DEFAULT_SNAPSHOT_EVERY
        if every < 1:
            raise ValueError(f"snapshot cadence must be >= 1, got {every}")
        if spill_every < 1:
            raise ValueError(
                f"spill_every must be >= 1, got {spill_every}")
        if attempt is None:
            attempt = int(os.environ.get("HOROVOD_ELASTIC_RESTART", "0"))
        # Manifests must record the TRUE world shape (the reshard-resume
        # remap runs off it), so default from the launcher environment,
        # not a hardcoded single-rank world.
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
        if world_size is None:
            world_size = int(os.environ.get("HOROVOD_SIZE", "1"))
        self.manager = manager
        self.every = int(every)
        self.spill_every = int(spill_every)
        self.rank = rank
        self.world_size = world_size
        self.attempt = attempt
        # Double buffer: _pending holds leaves whose d2h is in flight;
        # _latest holds the last COMMITTED (host numpy) snapshot.
        self._pending: Optional[Dict[str, Any]] = None
        self._latest: Optional[Dict[str, Any]] = None
        self._count = 0
        self.stats = {"snapshots": 0, "spills": 0,
                      "last_ms": None, "total_ms": 0.0}

    # ------------------------------------------------------------- cadence

    def check_alignment(self, steps_per_dispatch: int) -> None:
        if self.every % max(1, steps_per_dispatch):
            raise ValueError(
                f"snapshot cadence {self.every} is not a multiple of "
                f"steps_per_dispatch {steps_per_dispatch}: snapshots "
                "align to window boundaries (the host only holds a "
                "consistent state between dispatches) — round the "
                "cadence to a window multiple")

    def due(self, step: int) -> bool:
        return step > 0 and step % self.every == 0

    def maybe(self, step: int, state, **aux) -> bool:
        """Snapshot iff ``step`` is on the cadence. Returns whether one
        was taken. ``aux`` (``cursor=``, ``rng_key=``) flows into the
        resume manifest on spilling snapshots."""
        if not self.due(step):
            return False
        self.take(step, state, **aux)
        return True

    # ------------------------------------------------------------ snapshot

    def take(self, step: int, state, sync: bool = False, **aux) -> None:
        """Take one snapshot of ``state`` labelled ``step``.

        Async by default: commits the previous pending snapshot (its
        d2h has had a full cadence window to complete), then starts the
        new copy without blocking on it. ``sync=True`` (and every
        spill) completes the copy immediately. The state must NOT be
        donated to subsequent dispatches while a copy is in flight —
        the elastic loop therefore runs without donation.
        """
        t0 = time.perf_counter()
        self._commit_pending()
        spill = (self.manager is not None
                 and (self._count + 1) % self.spill_every == 0)
        record = {"step": int(step), "aux": dict(aux)}
        if sync or spill:
            record["tree"] = self._to_host(state, sync=True)
            self._latest = record
            self._pending = None
            if spill:
                self._spill(record)
        else:
            record["tree"] = self._to_host(state, sync=False)
            self._pending = record
        self._count += 1
        ms = (time.perf_counter() - t0) * 1e3
        self.stats["snapshots"] += 1
        self.stats["last_ms"] = ms
        self.stats["total_ms"] += ms

    def _to_host(self, state, sync: bool):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state)
        if sync:
            host = [np.asarray(l) for l in leaves]
        else:
            for l in leaves:
                if _is_jax_array(l):
                    l.copy_to_host_async()
            host = leaves  # completed (np.asarray) at commit time
        return {"leaves": host, "treedef": treedef, "synced": sync}

    def _commit_pending(self) -> None:
        if self._pending is None:
            return
        tree = self._pending["tree"]
        if not tree["synced"]:
            tree["leaves"] = [np.asarray(l) for l in tree["leaves"]]
            tree["synced"] = True
        self._latest = self._pending
        self._pending = None

    def _spill(self, record) -> None:
        import jax

        state = jax.tree_util.tree_unflatten(
            record["tree"]["treedef"], record["tree"]["leaves"])
        step = record["step"]
        self.manager.save(step, state)
        aux = record["aux"]
        rng_key = aux.get("rng_key")
        if rng_key is not None:
            rng_key = [int(w) for w in np.ravel(np.asarray(rng_key))]
        write_manifest(self.directory, ResumeManifest(
            step=step, world_size=self.world_size, rank=self.rank,
            attempt=self.attempt, cursor=aux.get("cursor"),
            rng_key=rng_key, wall_time=time.time()))
        self.stats["spills"] += 1

    # ------------------------------------------------------------ flush/IO

    @property
    def directory(self) -> Optional[str]:
        return getattr(self.manager, "directory", None)

    @property
    def latest(self):
        """(step, host-state) of the newest COMMITTED in-RAM snapshot,
        or None. Commits any pending transfer first."""
        import jax

        self._commit_pending()
        if self._latest is None:
            return None
        t = self._latest["tree"]
        return (self._latest["step"],
                jax.tree_util.tree_unflatten(t["treedef"], t["leaves"]))

    def flush(self, step: Optional[int] = None, state=None, **aux) -> None:
        """Final synchronous snapshot + spill (preemption epilogue and
        end-of-run). With ``state`` given, snapshots it at ``step`` and
        spills regardless of cadence; otherwise spills the newest in-RAM
        snapshot if it never reached disk. Blocks until the manager
        commits."""
        if state is not None:
            if step is None:
                raise ValueError(
                    "flush(state=...) needs the step label too: "
                    "flush(step, state) — the manifest records which "
                    "training step this final snapshot represents")
            self._commit_pending()
            record = {"step": int(step), "aux": dict(aux),
                      "tree": self._to_host(state, sync=True)}
            self._latest = record
            self._pending = None
            self._count += 1
            self.stats["snapshots"] += 1
            if self.manager is not None:
                self._spill(record)
        else:
            self._commit_pending()
            if self._latest is not None and self.manager is not None:
                steps = getattr(self.manager, "all_steps", lambda: [])()
                if self._latest["step"] not in steps:
                    self._spill(self._latest)
        if self.manager is not None:
            self.manager.wait_until_finished()

    def restore(self, template):
        """(state, manifest) from the newest committed manifest whose
        checkpoint exists, or None when there is nothing to resume.
        Walks older manifests if the newest points at a torn/missing
        checkpoint (crash between spill phases)."""
        if self.manager is None or self.directory is None:
            return None
        available = set(self.manager.all_steps())
        newest = latest_manifest(self.directory)
        candidates = []
        if newest is not None:
            candidates.append(newest)
        for step in reversed(manifest_steps(self.directory)):
            if newest is None or step != newest.step:
                m = read_manifest(self.directory, step)
                if m is not None:
                    candidates.append(m)
        for manifest in candidates:
            if manifest.step in available:
                state = self.manager.restore(manifest.step,
                                             template=template)
                return state, manifest
        return None
