"""Preemption signal handling: defer-to-step-boundary, then drain + save.

TPU preemptions (maintenance events, spot reclaim) arrive as SIGTERM
with a short grace window. The WRONG response is doing real work inside
the signal handler — a handler interrupts arbitrary code (possibly
mid-collective, mid-malloc, holding locks), so blocking collectives or
filesystem writes there deadlock or corrupt exactly when recovery
matters most (that anti-pattern is lint rule HVD007). The discipline
here:

1. the handler ONLY sets a flag (async-signal-safe by construction);
2. the training loop checks the flag at each step/window boundary —
   where the train state is consistent and no collective is mid-flight;
3. at the boundary, :meth:`PreemptionHandler.finalize` drains in-flight
   device work, writes one final SYNCHRONOUS snapshot through the
   :class:`~horovod_tpu.elastic.snapshot.Snapshotter`, and exits with
   the distinct :data:`EXIT_PREEMPTED` status (75, EX_TEMPFAIL) so the
   supervisor classifies the exit as *preempted* and relaunches.
"""

from __future__ import annotations

import os
import signal
import sys
from typing import Iterable, Optional

from horovod_tpu.run.driver import (EXIT_PREEMPTED,  # canonical home
                                    EXIT_RESIZED)

__all__ = ["PreemptionHandler", "Heartbeat", "EXIT_PREEMPTED",
           "namespaced_heartbeat_dir"]


def namespaced_heartbeat_dir(base: Optional[str] = None) -> str:
    """A heartbeat directory unique to ONE supervisor/fleet instance.

    ``HOROVOD_HEARTBEAT_DIR`` is exported to workers, so two watchdog
    owners sharing a directory on one host would watch each other's
    ``hb-<rank>`` files: supervisor A's rank 0 touching ``hb-0`` keeps
    supervisor B's stalled rank 0 alive forever (and vice versa), which
    silently defeats stall detection exactly when two jobs — or a
    training job and a serving fleet — colocate. Every watchdog owner
    therefore namespaces its directory per INSTANCE: a fresh unique
    subdirectory under ``base`` (or the system tempdir), never the
    shared path itself.
    """
    import tempfile
    import uuid

    if base:
        os.makedirs(base, exist_ok=True)
        path = os.path.join(
            base, f"hvd-hb-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(path)
        return path
    return tempfile.mkdtemp(prefix="hvd-heartbeat-")


class PreemptionHandler:
    """Deferred SIGTERM/preemption hook for elastic training loops.

    Usage::

        handler = PreemptionHandler()          # installs on SIGTERM
        for step in ...:
            if handler.triggered:              # boundary check
                handler.finalize(snapshotter, step, state)  # no return
            state, metrics = train_step(state, batch)

    ``install=False`` builds an uninstalled handler (driven purely by
    :meth:`trigger`, e.g. from the fault injector's deterministic
    ``preempt`` action). Context-manager form restores the previous
    handlers on exit.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,),
                 install: bool = True):
        self.triggered = False
        self.signum: Optional[int] = None
        #: exit status finalize() uses; a resize trigger overrides it
        #: with EXIT_RESIZED so the supervisor sees the incident class.
        self.exit_code: int = EXIT_PREEMPTED
        self._signals = tuple(signals)
        self._previous: dict = {}
        self._installed = False
        if install:
            self.install()

    def install(self) -> None:
        if self._installed:
            return
        for s in self._signals:
            self._previous[s] = signal.signal(s, self._on_signal)
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        # Flag-set ONLY: no collectives, no filesystem, no allocation —
        # the loop does the real work at its next step boundary (the
        # HVD007 discipline this module is the reference pattern for).
        self.triggered = True
        self.signum = signum

    def trigger(self, exit_code: Optional[int] = None) -> None:
        """Programmatic preemption request (same deferred semantics).
        ``exit_code`` overrides the finalize status — the resize fault
        action passes EXIT_RESIZED so the drain + final snapshot run
        exactly like a preemption but the supervisor relaunches at the
        requested world size."""
        self.triggered = True
        if exit_code is not None:
            self.exit_code = exit_code

    def check(self) -> bool:
        return self.triggered

    def finalize(self, snapshotter, step: int, state,
                 exit_code: Optional[int] = None, _exit=sys.exit,
                 **aux) -> None:
        """Boundary-time preemption epilogue; does not return.

        Drains in-flight device work (``jax.block_until_ready`` on the
        carried state — every issued collective completes or the
        runtime raises), takes one final SYNCHRONOUS snapshot spilled
        straight to disk with its resume manifest, and exits with
        ``exit_code`` so the supervisor sees a *preempted* worker, not
        a crash. ``aux`` is forwarded into the manifest (cursor, rng).
        """
        import jax

        if exit_code is None:
            exit_code = self.exit_code
        state = jax.block_until_ready(state)
        if snapshotter is not None:
            snapshotter.flush(step, state, **aux)
        kind = {EXIT_PREEMPTED: "preemption",
                EXIT_RESIZED: "resize"}.get(exit_code, f"exit {exit_code}")
        print(f"[hvd elastic] {kind} (signal {self.signum}): drained "
              f"and snapshotted at step {step}; exiting "
              f"{exit_code}", file=sys.stderr, flush=True)
        self.uninstall()
        _exit(exit_code)

    def __enter__(self) -> "PreemptionHandler":
        self.install()
        return self

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


class Heartbeat:
    """Worker-side liveness beacon for the supervisor's health watchdog.

    The elastic supervisor exports ``HOROVOD_HEARTBEAT_DIR``; each rank
    touches its per-rank file (``hb-<rank>``) at every window boundary
    — the same cadence snapshots, preemption checks and fault injection
    already use. The supervisor's :class:`~horovod_tpu.elastic.
    supervisor.HealthWatchdog` stats the mtimes: a rank whose file goes
    stale past the watchdog timeout is killed, classified *stalled* and
    relaunched — converting the today-unrecoverable silent hang (a
    ``stall:`` fault, a wedged collective below
    ``HOROVOD_NEGOTIATION_TIMEOUT``'s reach) into one bounded incident.

    A rank is only *watched* once its file exists — and the elastic
    loop takes its FIRST touch after the first window completes, so
    processes that are importing jax, compiling the first window, or
    never running the elastic loop at all are never killed for
    silence (the flip side: a stall before any window completes is
    outside the watchdog's reach). The touch is one tiny write — no
    device sync, no collective — cheap enough for every boundary.
    """

    FILE_FMT = "hb-{rank}"

    def __init__(self, directory: str, rank: Optional[int] = None):
        if rank is None:
            rank = int(os.environ.get("HOROVOD_RANK", "0"))
        self.rank = rank
        self.directory = directory
        self.path = os.path.join(directory, self.FILE_FMT.format(rank=rank))
        os.makedirs(directory, exist_ok=True)

    @classmethod
    def from_env(cls) -> Optional["Heartbeat"]:
        """A heartbeat bound to ``HOROVOD_HEARTBEAT_DIR``, or None when
        the job runs unsupervised (no watchdog, nothing to feed)."""
        directory = os.environ.get("HOROVOD_HEARTBEAT_DIR", "")
        return cls(directory) if directory else None

    def touch(self, step: Optional[int] = None) -> None:
        """Stamp liveness (mtime is the signal; the step content is for
        humans debugging a stale file)."""
        with open(self.path, "w") as f:
            f.write(f"{self.rank} {step if step is not None else ''}\n")
