"""Preemption signal handling: defer-to-step-boundary, then drain + save.

TPU preemptions (maintenance events, spot reclaim) arrive as SIGTERM
with a short grace window. The WRONG response is doing real work inside
the signal handler — a handler interrupts arbitrary code (possibly
mid-collective, mid-malloc, holding locks), so blocking collectives or
filesystem writes there deadlock or corrupt exactly when recovery
matters most (that anti-pattern is lint rule HVD007). The discipline
here:

1. the handler ONLY sets a flag (async-signal-safe by construction);
2. the training loop checks the flag at each step/window boundary —
   where the train state is consistent and no collective is mid-flight;
3. at the boundary, :meth:`PreemptionHandler.finalize` drains in-flight
   device work, writes one final SYNCHRONOUS snapshot through the
   :class:`~horovod_tpu.elastic.snapshot.Snapshotter`, and exits with
   the distinct :data:`EXIT_PREEMPTED` status (75, EX_TEMPFAIL) so the
   supervisor classifies the exit as *preempted* and relaunches.
"""

from __future__ import annotations

import signal
import sys
from typing import Iterable, Optional

from horovod_tpu.run.driver import EXIT_PREEMPTED  # canonical home

__all__ = ["PreemptionHandler", "EXIT_PREEMPTED"]


class PreemptionHandler:
    """Deferred SIGTERM/preemption hook for elastic training loops.

    Usage::

        handler = PreemptionHandler()          # installs on SIGTERM
        for step in ...:
            if handler.triggered:              # boundary check
                handler.finalize(snapshotter, step, state)  # no return
            state, metrics = train_step(state, batch)

    ``install=False`` builds an uninstalled handler (driven purely by
    :meth:`trigger`, e.g. from the fault injector's deterministic
    ``preempt`` action). Context-manager form restores the previous
    handlers on exit.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,),
                 install: bool = True):
        self.triggered = False
        self.signum: Optional[int] = None
        self._signals = tuple(signals)
        self._previous: dict = {}
        self._installed = False
        if install:
            self.install()

    def install(self) -> None:
        if self._installed:
            return
        for s in self._signals:
            self._previous[s] = signal.signal(s, self._on_signal)
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        # Flag-set ONLY: no collectives, no filesystem, no allocation —
        # the loop does the real work at its next step boundary (the
        # HVD007 discipline this module is the reference pattern for).
        self.triggered = True
        self.signum = signum

    def trigger(self) -> None:
        """Programmatic preemption request (same deferred semantics)."""
        self.triggered = True

    def check(self) -> bool:
        return self.triggered

    def finalize(self, snapshotter, step: int, state,
                 exit_code: int = EXIT_PREEMPTED, _exit=sys.exit,
                 **aux) -> None:
        """Boundary-time preemption epilogue; does not return.

        Drains in-flight device work (``jax.block_until_ready`` on the
        carried state — every issued collective completes or the
        runtime raises), takes one final SYNCHRONOUS snapshot spilled
        straight to disk with its resume manifest, and exits with
        ``exit_code`` so the supervisor sees a *preempted* worker, not
        a crash. ``aux`` is forwarded into the manifest (cursor, rng).
        """
        import jax

        state = jax.block_until_ready(state)
        if snapshotter is not None:
            snapshotter.flush(step, state, **aux)
        print(f"[hvd elastic] preemption (signal {self.signum}): drained "
              f"and snapshotted at step {step}; exiting "
              f"{exit_code} (preempted)", file=sys.stderr, flush=True)
        self.uninstall()
        _exit(exit_code)

    def __enter__(self) -> "PreemptionHandler":
        self.install()
        return self

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False
