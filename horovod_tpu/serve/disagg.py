"""Disaggregated prefill/decode serving: the KV handoff coordinator.

``FleetConfig(pools={"prefill": P, "decode": D})`` splits the fleet
into two pools behind the existing router (replica ids ``0..P-1``
prefill, the rest decode; the mapping is positional and survives
relaunches). The fleet then stamps ``prefill_only=True`` on every
dispatch: a prefill replica runs the request's chunked prefill to
completion, emits the first token, and PARKS it in its engine's
handoff bay (:attr:`ServeEngine.handoff
<horovod_tpu.serve.engine.ServeEngine.handoff>`) with the finished KV
pages held. Each fleet tick, :class:`DisaggCoordinator` sweeps the
prefill pool for parked requests and, for each, picks a decode
replica with the SAME policy the router uses for admission
(:func:`~horovod_tpu.serve.router.pick_replica` over the decode pool
only: the existing load keys + prefix-affinity) and ships the pages:

* **wire transports** (process/tcp): the worker RPC verbs
  ``kv_export_begin/chunk/end`` and ``kv_import_begin/chunk/commit``
  (:mod:`~horovod_tpu.serve.kv_wire` over
  :mod:`~horovod_tpu.serve.chunk_stream` — per-chunk crc32, whole-blob
  sha256 digest-verify at commit, resume-from-offset via
  ``import_begin``'s ``have_bytes``);
* **inproc**: the two engines directly, but through the SAME
  KvSender/KvReceiver chunk codec — ``kv_bytes_shipped`` and the
  framing checks mean the same thing on every transport.

Ownership moves exactly once, in this order: the decode side's
digest-verified ``commit`` admits the request into its engine at the
handoff position → the ROUTER's bookkeeping moves (``assigned`` lists,
``req.replica``, proxy mirrors) → the prefill side releases the pages
(``kv_export_end commit=True`` — no terminal event; the stream did
not end). The inproc lane swaps the last two steps (release BEFORE
admit): the Request object is shared between the engines, and
``admit_prefilled`` rewrites ``req.pages``/``page_table`` in place —
releasing after would free the decode side's live grant.

Failure modes are first-class and reuse shipped machinery — a KV
transfer is NEVER retried across a :class:`TransportError` (unlike
the params-push lane):

* **prefill side dies mid-transfer** (or a ``partition:`` netfault on
  its host tears the KV channel): ``_transport_death`` → the replica's
  ``assigned`` drains through ``rebase_for_recompute`` → requeue at
  the head, at-most-once; the decode side's partial import is aborted
  best-effort (its assembled bytes are dropped — a redispatch
  re-prefills anyway).
* **decode side dies mid-transfer**: its own death path; the request
  STAYS PARKED on the healthy prefill replica (pages held) and the
  next tick retries against another decode replica — the sender is
  dropped (``commit=False``) and re-created; the export is
  bit-identical by construction.
* **decode pool saturated / no eligible replica**: the request simply
  stays parked — no spin, no drop; parked requests count against the
  prefill replica's in-flight (so admission backpressure holds) and
  keep their TTL (the engine's deadline sweep covers the bay).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from horovod_tpu.serve.kv_wire import KvReceiver, KvSender
from horovod_tpu.serve.router import pick_replica
from horovod_tpu.serve.transport import TransportError


def _log(msg: str) -> None:
    print(f"[disagg] {msg}", flush=True)


class DisaggCoordinator:
    """Per-fleet KV-handoff driver, invoked once per fleet tick (after
    every replica stepped — the handoff snapshots are fresh). Holds
    only transfer metrics and the one-shot test fault hook; all
    request/replica state lives in the fleet's own bookkeeping."""

    def __init__(self, fleet):
        self.fleet = fleet
        self.transfers = 0
        self.kv_bytes_shipped = 0
        self.chunks_shipped = 0
        self.transfer_ms: List[float] = []
        #: transfer failures by side ("prefill"/"decode"), each one a
        #: replica-death incident routed through the fleet's machinery.
        self.failures: Dict[str, int] = {}
        #: One-shot deterministic fault hook for tests: "prefill" or
        #: "decode" makes the NEXT transfer die mid-chunk-loop on that
        #: side (synthetic TransportError into the genuine death
        #: path), exactly the shape a partition: netfault produces.
        self.fault_next_transfer: Optional[str] = None

    # ------------------------------------------------------------ pools

    def prefill_pool(self) -> List:
        return [r for r in self.fleet.replicas if r.role == "prefill"]

    def decode_pool(self) -> List:
        return [r for r in self.fleet.replicas if r.role == "decode"]

    # ------------------------------------------------------------- tick

    def step(self, now: float) -> int:
        """Sweep the prefill pool and ship every parked request a
        decode replica will take. Returns transfers completed (the
        fleet folds it into tick progress)."""
        moved = 0
        for prep in list(self.prefill_pool()):
            if not prep.healthy or prep.engine is None:
                continue
            for rid in list(self._handoff_rids(prep)):
                if not prep.healthy:
                    break   # a transfer failure killed it mid-sweep
                req = next((r for r in prep.assigned if r.rid == rid),
                           None)
                if req is None:
                    continue   # drained/expired between snapshots
                drep = pick_replica(self.decode_pool(), req,
                                    self.fleet._route_key(req))
                if drep is None:
                    continue   # decode pool busy/down: stays parked
                if self._transfer(prep, drep, req, now):
                    moved += 1
        return moved

    def _handoff_rids(self, rep) -> List[int]:
        if rep.transport == "inproc":
            return list(rep.engine.handoff_ready())
        return list(getattr(rep.engine, "handoff_rids", ()))

    # -------------------------------------------------------- transfer

    def _transfer(self, prep, drep, req, now: float) -> bool:
        t0 = time.perf_counter()
        if prep.transport == "inproc":
            ok = self._transfer_inproc(prep, drep, req, now)
        else:
            ok = self._transfer_wire(prep, drep, req, now)
        if ok:
            self.transfers += 1
            self.transfer_ms.append((time.perf_counter() - t0) * 1e3)
        return ok

    def _consume_fault(self, side: str) -> bool:
        if self.fault_next_transfer != side:
            return False
        self.fault_next_transfer = None
        return True

    def _record_failure(self, side: str) -> None:
        self.failures[side] = self.failures.get(side, 0) + 1

    def _move(self, prep, drep, req, now: float,
              streamed: int) -> None:
        """The at-most-once ownership move, in ROUTER bookkeeping: the
        request leaves the prefill replica's assigned list for the
        decode replica's, and (wire transports) the proxy mirrors
        move with it — the decode proxy starts collecting PAST the
        tokens the router already streamed (``streamed``), so the
        handoff token is never re-emitted."""
        prep.assigned = [r for r in prep.assigned if r is not req]
        drep.assigned.append(req)
        req.replica = drep.id
        if prep.transport != "inproc":
            pproxy, dproxy = prep.engine, drep.engine
            pproxy._by_rid.pop(req.rid, None)
            pproxy._streamed.pop(req.rid, None)
            pproxy._prefix_seen.pop(req.rid, None)
            dproxy._by_rid[req.rid] = req
            dproxy._streamed[req.rid] = streamed
            dproxy._prefix_seen[req.rid] = (0, 0)

    # ---------------------------------------------------- inproc lane

    def _transfer_inproc(self, prep, drep, req, now: float) -> bool:
        """Both engines in this process — same codec, same ordering
        discipline, except release-before-admit (see the module
        docstring: the Request object is SHARED)."""
        fleet = self.fleet
        peng, deng = prep.engine, drep.engine
        blob = peng.export_handoff(req.rid)
        sender = KvSender(blob, req.rid, fleet.fleet.push_chunk_bytes)
        recv = KvReceiver(req.rid)
        recv.begin(sender.manifest)
        tear_at = sender.num_chunks // 2
        for i in range(sender.num_chunks):
            if i == tear_at and self._consume_fault("prefill"):
                self._record_failure("prefill")
                _log(f"request {req.rid}: prefill replica {prep.id} "
                     "died mid-transfer (injected) — drain/requeue")
                fleet._kill_replica(prep, code=1, stalled=False,
                                    now=now)
                return False
            if i == tear_at and self._consume_fault("decode"):
                self._record_failure("decode")
                _log(f"request {req.rid}: decode replica {drep.id} "
                     "died mid-transfer (injected) — request stays "
                     f"parked on prefill replica {prep.id}")
                fleet._kill_replica(drep, code=1, stalled=False,
                                    now=now)
                return False
            recv.write_chunk(sender.chunk(i))
        verified = recv.commit()   # digest-verified, same as the wire
        self.kv_bytes_shipped += sender.total_bytes
        self.chunks_shipped += sender.num_chunks
        # SHARED Request: release the prefill side's pages BEFORE
        # admit rewrites req.pages/page_table with the decode grant.
        peng.release_handoff(req.rid)
        req.prefill_only = False
        try:
            deng.admit_prefilled(req, verified)
        except Exception as e:
            # Decode-side admit failed (pages filled since the
            # eligibility check): the prefill pages are already gone,
            # so take the shipped recovery path — rebase + requeue at
            # the head, at-most-once (exactly a drain of one request).
            self._record_failure("decode")
            _log(f"request {req.rid}: decode admit failed "
                 f"({type(e).__name__}: {e}) — rebase + requeue")
            self._requeue(prep, req, now)
            return False
        self._move(prep, drep, req, now, streamed=len(req.generated))
        return True

    def _requeue(self, prep, req, now: float) -> None:
        """One request's edition of the fleet drain: rebase
        generated-so-far into the prompt and requeue at the head
        (at-most-once — nothing already streamed is re-emitted)."""
        from horovod_tpu.serve.scheduler import (RequestState,
                                                 rebase_for_recompute)

        fleet = self.fleet
        prep.assigned = [r for r in prep.assigned if r is not req]
        req.pages = []
        req.page_table = None
        fleet.tokens_recomputed_total += \
            req.prefill_pos + len(req.generated)
        if req.prefix_hits_at_drain is not None:
            fleet.redispatch_prefix_saved += max(
                0, req.prefix_hit_tokens - req.prefix_hits_at_drain)
        req.prefix_hits_at_drain = req.prefix_hit_tokens
        if rebase_for_recompute(req):
            req.state = RequestState.QUEUED
            req.requeued = True
            req.redispatches += 1
            fleet.queue.insert(0, req)
            fleet.redispatched_total += 1
        else:
            req.state = RequestState.FINISHED
            req.t_finish = now
            if req.t_admit is not None:
                fleet._service_samples.append(now - req.t_admit)
            fleet.finished.append(req)

    # ------------------------------------------------------- wire lane

    def _transfer_wire(self, prep, drep, req, now: float) -> bool:
        """Process/tcp transports: drive the worker KV verbs. Every
        TransportError routes into the throwing SIDE's death path —
        never a blind RPC retry (at-most-once would not survive one).
        A synthetic injected tear takes the same path, so tests pin
        the identical recovery shape a real partition produces."""
        fleet = self.fleet
        rid = req.rid
        pcli, dcli = prep.engine.client, drep.engine.client
        streamed = len(req.generated)
        try:
            m = pcli.call("kv_export_begin", {
                "rid": rid,
                "chunk_bytes": fleet.fleet.push_chunk_bytes,
            })["manifest"]
        except TransportError as e:
            self._prefill_died(prep, drep, rid, e, now)
            return False
        payload = {
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "eos_token": req.eos_token,
            "seed": int(req.seed),
            "age": max(0.0, now - req.arrival),
            "ttl": req.ttl,
            "generated": [int(t) for t in req.generated],
        }
        try:
            have = int(dcli.call("kv_import_begin", {
                "rid": rid, "manifest": m, "req": payload,
            })["have_bytes"])
        except TransportError as e:
            self._decode_died(prep, drep, rid, e, now)
            return False
        n = int(m["num_chunks"])
        start = have // int(m["chunk_bytes"])
        tear_at = max(start, start + (n - start) // 2)
        shipped = 0
        for i in range(start, n):
            try:
                if i == tear_at and self._consume_fault("prefill"):
                    raise TransportError(
                        "injected: prefill side torn mid-transfer")
                c = pcli.call("kv_export_chunk",
                              {"rid": rid, "index": i})["chunk"]
            except TransportError as e:
                self._prefill_died(prep, drep, rid, e, now)
                return False
            try:
                if i == tear_at and self._consume_fault("decode"):
                    raise TransportError(
                        "injected: decode side torn mid-transfer")
                dcli.call("kv_import_chunk",
                          {"rid": rid, "chunk": c})
            except TransportError as e:
                self._decode_died(prep, drep, rid, e, now)
                return False
            shipped += int(c["size"])
        try:
            dcli.call("kv_import_commit", {"rid": rid})
        except TransportError as e:
            self._decode_died(prep, drep, rid, e, now)
            return False
        # Committed on the decode side: the ownership move happens NOW
        # (router truth), before the prefill-side release — a release
        # failure past this point costs only the dead replica's pages.
        self.kv_bytes_shipped += shipped
        self.chunks_shipped += max(0, n - start)
        self._move(prep, drep, req, now, streamed=streamed)
        try:
            pcli.call("kv_export_end", {"rid": rid, "commit": True})
        except TransportError as e:
            # The request already lives on the decode side; the
            # prefill replica alone dies (its parked pages die with
            # its engine — nothing to leak).
            self._record_failure("prefill")
            fleet._transport_death(prep, e, now)
        return True

    def _prefill_died(self, prep, drep, rid, err, now: float) -> None:
        """Prefill-side transport failure: its death path drains the
        parked request (rebase + requeue, at-most-once); the decode
        side's partial import is aborted best-effort."""
        fleet = self.fleet
        self._record_failure("prefill")
        _log(f"request {rid}: prefill replica {prep.id} lost "
             f"mid-transfer ({type(err).__name__}) — drain/requeue")
        fleet._transport_death(prep, err, now)
        if drep.healthy and drep.engine is not None:
            try:
                drep.engine.client.call("kv_import_abort",
                                        {"rid": rid})
            except TransportError as e2:
                self._record_failure("decode")
                fleet._transport_death(drep, e2, now)

    def _decode_died(self, prep, drep, rid, err, now: float) -> None:
        """Decode-side transport failure: its death path runs; the
        request stays parked on the healthy prefill replica (pages
        held), whose sender is dropped — the next tick re-exports
        bit-identically toward another decode replica."""
        fleet = self.fleet
        self._record_failure("decode")
        _log(f"request {rid}: decode replica {drep.id} lost "
             f"mid-transfer ({type(err).__name__}) — request stays "
             f"parked on prefill replica {prep.id}")
        fleet._transport_death(drep, err, now)
        if prep.healthy and prep.engine is not None:
            try:
                prep.engine.client.call(
                    "kv_export_end", {"rid": rid, "commit": False})
            except TransportError as e2:
                self._record_failure("prefill")
                fleet._transport_death(prep, e2, now)

    # ---------------------------------------------------------- stats

    def reset_metrics(self) -> None:
        self.transfers = 0
        self.kv_bytes_shipped = 0
        self.chunks_shipped = 0
        self.transfer_ms = []
        self.failures = {}

    def stats(self) -> Dict:
        from horovod_tpu.serve.metrics import percentile

        s = self.transfer_ms
        return {
            "pools": {"prefill": len(self.prefill_pool()),
                      "decode": len(self.decode_pool())},
            "transfers": self.transfers,
            "kv_bytes_shipped": self.kv_bytes_shipped,
            "chunks_shipped": self.chunks_shipped,
            "transfer_ms_p50": round(percentile(s, 50), 4) if s
            else None,
            "transfer_ms_p99": round(percentile(s, 99), 4) if s
            else None,
            "parked": sum(len(self._handoff_rids(r))
                          for r in self.prefill_pool()
                          if r.healthy and r.engine is not None),
            "failures": dict(self.failures),
        }


__all__ = ["DisaggCoordinator"]
