"""Fault-tolerant multi-replica serving: N engines behind one router.

PR 9 made training survive real clusters (classified worker exits,
heartbeat watchdog, budgeted relaunches); this module gives serving the
same story instead of reinventing it. A :class:`ServeFleet` runs N
:class:`~horovod_tpu.serve.engine.ServeEngine` replicas behind a
least-loaded router (:mod:`~horovod_tpu.serve.router`), and every
failure mode is first-class:

* **replica death** (``kill:`` faults, real crashes) is drained and
  **redispatched**: the router — which streamed every emitted token to
  the client and therefore knows each request's generated-so-far
  prefix — re-submits unfinished requests to survivors with the prefix
  folded into the prompt (:func:`~horovod_tpu.serve.scheduler.
  rebase_for_recompute`, the same arithmetic as eviction-recompute).
  Tokens already emitted are NEVER re-emitted (at-most-once), and
  greedy output stays bit-identical to an uninterrupted run (pinned in
  tests/test_serve_fleet.py and the ``serve_bench --fleet`` A/B);
* **silent stalls** become classified incidents: every live replica's
  per-replica heartbeat file is stamped at the END of each fleet tick
  (all together, once every replica has stepped — see :meth:`ServeFleet.
  step` for why per-step stamping would mis-kill healthy peers), and a
  :class:`~horovod_tpu.elastic.supervisor.HealthWatchdog` (PR 9's, not
  a copy) kills any replica stale past the timeout — classified
  ``stalled`` via :class:`~horovod_tpu.run.driver.WorkerExit`, exactly
  the training taxonomy;
* **relaunch** consumes a fleet-wide restart budget with exponential
  backoff (the anti-pattern of an unbudgeted, backoff-less retry loop
  is lint rule HVD010); a replica past the budget is ``failed`` and the
  fleet degrades;
* a degraded fleet **sheds load** instead of letting TTFT diverge: the
  router's admission queue is bounded (``FleetConfig.max_queue``), and
  overflow is rejected terminally — ``reject_reason="overloaded"``
  with a ``retry_after`` hint — while requests that can NEVER fit the
  replica geometry reject as ``infeasible``. Rejected requests never
  touch a replica, so they can never allocate KV pages (allocator
  conservation is pinned in tests).

Replicas here are in-process engines with a process-shaped lifecycle
(real heartbeat files, the real watchdog, the real exit taxonomy with
synthetic ``-SIGKILL`` codes): that keeps the whole recovery story —
including the bit-exact redispatch pin — CI-exercisable on CPU in
seconds, with deterministic fault injection
(:func:`~horovod_tpu.elastic.faults.parse_serve_fault_plan`) and an
injectable clock. What stays honest about the real multi-process fleet:
the router's drain uses only router-side bookkeeping (dispatched
requests + streamed tokens), never the dead engine's internals, and a
crash loses the replica's engine state wholesale. docs/serving.md "The
fleet" covers the runbook.
"""

from __future__ import annotations

import os
import signal as _signal
import sys
import time
from typing import Dict, List, Optional, Sequence, Union

from horovod_tpu.elastic.faults import (FaultPlanError, ServeFaultAction,
                                        parse_serve_fault_plan)
from horovod_tpu.elastic.signals import Heartbeat, namespaced_heartbeat_dir
from horovod_tpu.elastic.supervisor import HealthWatchdog
from horovod_tpu.run.driver import WorkerExit
from horovod_tpu.serve.config import FleetConfig, ServeConfig
from horovod_tpu.serve.engine import ServeEngine
from horovod_tpu.serve.router import (pick_replica, replica_load,
                                      retry_after_hint)
from horovod_tpu.serve.scheduler import (Request, RequestState,
                                         rebase_for_recompute)


def _log(msg: str) -> None:
    print(f"[hvd fleet] {msg}", file=sys.stderr, flush=True)


class Replica:
    """One engine + its process-shaped lifecycle.

    ``state``: ``healthy`` (serving; may currently be stalled or
    slowed by a fault) -> ``dead`` (killed; relaunch pending behind the
    backoff) -> ``healthy`` again, or ``failed`` (terminal: the restart
    budget is spent). ``assigned`` is the ROUTER's bookkeeping —
    dispatched-but-unfinished requests — and is what drain/redispatch
    reads, never the engine's internals (a crashed engine's state is
    gone).
    """

    def __init__(self, rid: int, engine: ServeEngine, heartbeat: Heartbeat):
        self.id = rid
        self.engine: Optional[ServeEngine] = engine
        self.heartbeat = heartbeat
        self.state = "healthy"
        self.assigned: List[Request] = []
        self.exit: Optional[WorkerExit] = None
        self.restarts = 0               # relaunches consumed so far
        self.relaunch_at: Optional[float] = None
        self.stall_until: Optional[float] = None   # None = not stalled
        self.slow_factor = 1.0
        self.steps = 0

    @property
    def healthy(self) -> bool:
        return self.state == "healthy"


class ServeFleet:
    """N continuous-batching replicas behind a fault-tolerant router.

    ``params``/``config`` build each replica's engine (one geometry
    fleet-wide); ``fleet`` sizes the fleet and its recovery policy.
    ``clock`` and ``sleep`` are injectable for deterministic tests —
    the heartbeat/watchdog lane alone reads real file mtimes, so stall
    detection tests run on the wall clock (slow-marked).

    The lifecycle mirrors :class:`ServeEngine`: :meth:`submit` admits
    (or sheds), :meth:`step` runs one fleet tick (faults -> watchdog ->
    relaunches -> dispatch -> one engine step per live replica),
    :meth:`run` drains to idle, :meth:`stats` aggregates SLO + recovery
    metrics.
    """

    def __init__(self, params: Dict, config: ServeConfig,
                 fleet: Optional[FleetConfig] = None, *,
                 chips_per_replica: int = 1,
                 clock=time.perf_counter, sleep=time.sleep):
        self.params = params
        self.config = config
        self.fleet = fleet if fleet is not None else FleetConfig()
        self.chips_per_replica = chips_per_replica
        self.chips = chips_per_replica * self.fleet.replicas
        self.clock = clock
        self._sleep = sleep

        # Static admission geometry (survives every replica dying):
        # exactly PagedKVCache.fits, computed off params + config —
        # capacity derived from the kvcache module's own constant so
        # router and engines can never disagree on the reserved count.
        from horovod_tpu.serve.kvcache import allocatable_pages

        self._lmax = int(params["pos"].shape[0])
        self._page_capacity = allocatable_pages(config.num_pages)

        # Router state.
        self.queue: List[Request] = []
        self.rejected: List[Request] = []
        self.finished: List[Request] = []
        self.timed_out: List[Request] = []
        self.evicted: List[Request] = []    # engine-terminal evictions
        # admit->finish secs feeding retry_after_hint — a BOUNDED
        # recency window, not the full history: the hint is recomputed
        # on every overloaded rejection (hot exactly when shedding is),
        # and recent service times describe a degraded fleet better
        # than its lifetime average anyway.
        import collections

        self._service_samples = collections.deque(maxlen=256)

        # Recovery metrics.
        self.incidents: List[Dict] = []
        self.incidents_by_class: Dict[str, int] = {}
        self.redispatched_total = 0
        self.tokens_recomputed_total = 0
        self.shed_total = 0
        self.restarts_used = 0

        self.occupancy_samples: List[float] = []
        self.steps = 0
        self._t_start = clock()

        # Fault plan (armed via arm_fault_plan; fires on the clock).
        self._pending_faults: List[tuple] = []   # (fire_at_s, action)
        self._fault_t0: Optional[float] = None

        # Supervision: heartbeat dir namespaced per fleet INSTANCE so
        # colocated fleets/supervisors never watch each other's files.
        self.heartbeat_dir = namespaced_heartbeat_dir(
            self.fleet.heartbeat_dir)
        self.watchdog: Optional[HealthWatchdog] = None
        if self.fleet.watchdog_timeout > 0:
            self.watchdog = HealthWatchdog(
                self.heartbeat_dir, self.fleet.watchdog_timeout,
                interval=min(0.5, self.fleet.watchdog_timeout / 2))

        self._closed = False
        self.replicas: List[Replica] = [
            self._spawn(i) for i in range(self.fleet.replicas)]

    def close(self) -> None:
        """Release the fleet's host-side footprint — the per-instance
        heartbeat directory (uniquely named by construction, so a
        long-lived service or bench loop constructing fleets repeatedly
        would otherwise accumulate one directory per instance under the
        base/tempdir forever). Idempotent; a closed fleet can no longer
        step. Context-manager form closes on exit."""
        if self._closed:
            return
        self._closed = True
        import shutil

        shutil.rmtree(self.heartbeat_dir, ignore_errors=True)

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------- lifecycle

    def _spawn(self, rid: int) -> Replica:
        engine = ServeEngine(self.params, self.config,
                             chips=self.chips_per_replica,
                             clock=self.clock)
        hb = Heartbeat(self.heartbeat_dir, rank=rid)
        # A (re)spawned replica is unwatched until its first completed
        # step: no stale file from a previous incarnation may insta-kill
        # it while it recompiles.
        try:
            os.unlink(hb.path)
        except OSError:
            pass
        return Replica(rid, engine, hb)

    @property
    def in_flight(self) -> int:
        return sum(len(r.assigned) for r in self.replicas) + \
            len(self.queue)

    @property
    def idle(self) -> bool:
        return self.in_flight == 0

    @property
    def alive(self) -> bool:
        """At least one replica is serving or can still come back."""
        return any(r.state != "failed" for r in self.replicas)

    # ------------------------------------------------------ fault plan

    def arm_fault_plan(self, plan: Union[str, Sequence[ServeFaultAction]],
                       horizon: Optional[float] = None) -> None:
        """Arm a serving fault plan (string grammar or parsed actions).
        Fire offsets are measured from the fault epoch — the fleet's
        first step, re-anchored only by :meth:`reset_metrics` (the
        bench's measurement start) — NEVER by arming itself: a second
        mid-run arm must not silently shift the fire times of actions
        already armed. An offset already in the past fires at the next
        step. ``horizon`` resolves percent ``at=`` forms (e.g. the
        bench passes its last workload arrival); replica ids are
        validated against the fleet size fail-fast."""
        actions = (parse_serve_fault_plan(plan)
                   if isinstance(plan, str) else list(plan))
        for a in actions:
            # Hand-built actions get the parser's fail-fast contract
            # too — a malformed one must raise HERE, not TypeError
            # out of the fleet loop at fire time.
            a.validate()
            if not 0 <= a.replica < len(self.replicas):
                raise FaultPlanError(
                    f"fault action {a}: replica {a.replica} is outside "
                    f"this fleet (replicas 0..{len(self.replicas) - 1})")
        self._pending_faults.extend(
            (a.resolve_at(horizon), a) for a in actions)
        self._pending_faults.sort(key=lambda p: p[0])

    def _inject_faults(self, now: float) -> None:
        if not self._pending_faults:
            return
        t = now - self._fault_t0
        while self._pending_faults and self._pending_faults[0][0] <= t:
            _, action = self._pending_faults.pop(0)
            rep = self.replicas[action.replica]
            _log(f"fault injection: {action} firing (replica state "
                 f"{rep.state})")
            if action.kind == "kill":
                if rep.healthy:
                    self._kill_replica(rep, code=-int(_signal.SIGKILL),
                                       stalled=False, now=now)
            elif action.kind == "stall":
                if rep.healthy:
                    rep.stall_until = (now + action.secs
                                       if action.secs is not None
                                       else float("inf"))
            elif action.kind == "slow":
                # Like kill/stall: a fault addressed to a dead replica
                # is a no-op — it must not brand the NEXT incarnation
                # (kill resets slow_factor to 1.0 for the same reason).
                if rep.healthy:
                    rep.slow_factor = float(action.factor)

    # ------------------------------------------------------ submission

    def _fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """PagedKVCache.fits without a live engine — the SAME
        :func:`~horovod_tpu.serve.kvcache.fits_geometry` predicate, so
        admission control keeps answering (and rejecting honestly)
        while every replica is mid-relaunch and can never drift from
        what the engines would admit."""
        from horovod_tpu.serve.kvcache import fits_geometry

        return fits_geometry(prompt_len, max_new_tokens,
                             max_len=self._lmax,
                             page_size=self.config.page_size,
                             capacity=self._page_capacity)

    def _healthy_slots(self) -> int:
        return sum(r.engine.config.decode_slots for r in self.replicas
                   if r.healthy and r.engine is not None)

    def _reject(self, req: Request, reason: str,
                retry_after: Optional[float] = None) -> Request:
        req.state = RequestState.REJECTED
        req.reject_reason = reason
        req.retry_after = retry_after
        self.rejected.append(req)
        if reason == "overloaded":
            self.shed_total += 1
        return req

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               eos_token: Optional[int] = None, seed: int = 0,
               arrival: Optional[float] = None,
               ttl: Optional[float] = None) -> Request:
        """Admit one request at the router (same surface as
        :meth:`ServeEngine.submit`). Check ``state`` — ``rejected``
        carries ``reject_reason`` (``infeasible``: can never run on
        this geometry; ``overloaded``: the bounded queue is full or the
        fleet is permanently down — retry after ``retry_after`` when
        it is not None)."""
        from horovod_tpu.serve.scheduler import make_request

        req = make_request(self.config, self.clock, prompt,
                           max_new_tokens, temperature=temperature,
                           top_k=top_k, eos_token=eos_token, seed=seed,
                           arrival=arrival, ttl=ttl)
        if not self._fits(req.prompt_len, req.max_new_tokens):
            return self._reject(req, "infeasible")
        if not self.alive:
            # Permanently degraded to zero replicas: shed with no hint
            # (there is no "later" this fleet can promise).
            return self._reject(req, "overloaded")
        if self.fleet.max_queue and \
                len(self.queue) >= self.fleet.max_queue:
            hint = retry_after_hint(
                len(self.queue), max(1, self._healthy_slots()),
                self._service_samples, self.fleet.retry_after_min)
            return self._reject(req, "overloaded", round(hint, 4))
        req.state = RequestState.QUEUED
        self.queue.append(req)
        return req

    # ---------------------------------------------------- supervision

    def _kill_replica(self, rep: Replica, *, code: int, stalled: bool,
                      now: float, detect_age: Optional[float] = None
                      ) -> None:
        """Classify + drain + schedule relaunch: the fleet edition of
        the supervisor's per-incident policy."""
        rep.exit = WorkerExit(rank=rep.id, code=code, stalled=stalled)
        category = rep.exit.category
        self.incidents_by_class[category] = \
            self.incidents_by_class.get(category, 0) + 1
        moved, recomputed = self._drain(rep, now)
        # The engine object (pages, allocator, compiled-step cache) is
        # dropped wholesale — the crash shape. Its heartbeat file goes
        # too so the relaunch starts unwatched.
        rep.engine = None
        rep.state = "dead"
        rep.stall_until = None
        rep.slow_factor = 1.0
        try:
            os.unlink(rep.heartbeat.path)
        except OSError:
            pass
        backoff = min(self.fleet.backoff_cap,
                      self.fleet.backoff_base * (2 ** rep.restarts))
        rep.relaunch_at = now + backoff
        self.incidents.append({
            "replica": rep.id,
            "category": category,
            "code": code,
            "t_s": round(now - self._t_start, 4),
            # Watchdog kills carry the observed heartbeat age (real
            # detection latency). In-process crashes are observed
            # synchronously — 0.0 is honest here where a multi-process
            # fleet would pay one supervision-poll interval.
            "detect_s": round(detect_age, 4) if detect_age is not None
            else 0.0,
            "redispatched": moved,
            "tokens_recomputed": recomputed,
            "backoff_s": round(backoff, 4),
        })
        _log(f"{rep.exit.describe(role='replica')} — drained {moved} "
             f"request(s) to survivors ({recomputed} KV tokens to "
             f"recompute); relaunch in {backoff:g}s")

    def _drain(self, rep: Replica, now: float) -> tuple:
        """Recover every dispatched-but-unfinished request of a dead
        replica from ROUTER bookkeeping: rebase generated-so-far into
        the prompt and requeue at the HEAD (they already consumed
        service), preserving their relative order. Returns
        ``(redispatched, kv_tokens_to_recompute)``."""
        moved: List[Request] = []
        recomputed = 0
        terminal = {
            RequestState.FINISHED: self.finished,
            RequestState.TIMEOUT: self.timed_out,
            RequestState.REJECTED: self.rejected,
            RequestState.EVICTED: self.evicted,
        }
        for req in rep.assigned:
            dest = terminal.get(req.state)
            if dest is not None:
                # Terminal but not yet collected — the replica died in
                # the very step that finished/expired it, before the
                # end-of-tick _collect ran (e.g. its engine raised
                # mid-step). The router's streamed-token truth stands:
                # route it to the fleet list, never drop it.
                if not any(r is req for r in dest):
                    dest.append(req)
                continue
            # The dead engine's pages died with it; only the request's
            # host-side bookkeeping survives.
            req.pages = []
            req.page_table = None
            recomputed += req.prefill_pos + len(req.generated)
            if rebase_for_recompute(req):
                req.state = RequestState.QUEUED
                req.requeued = True
                req.redispatches += 1
                moved.append(req)
            else:
                # Killed after its last token was emitted but before
                # the bookkeeping finished it: nothing left to
                # generate — finish, never re-emit (at-most-once).
                req.state = RequestState.FINISHED
                req.t_finish = now
                if req.t_admit is not None:
                    # same service-time sample _collect would stamp —
                    # incident-affected requests must not vanish from
                    # the retry-after estimate.
                    self._service_samples.append(now - req.t_admit)
                self.finished.append(req)
        rep.assigned = []
        self.queue[0:0] = moved
        self.redispatched_total += len(moved)
        self.tokens_recomputed_total += recomputed
        return len(moved), recomputed

    def _check_watchdog(self, now: float) -> None:
        if self.watchdog is None:
            return
        live = [r.id for r in self.replicas if r.healthy]
        for rid, age in self.watchdog.check(live).items():
            rep = self.replicas[rid]
            self.watchdog.kills[rid] = age
            _log(f"health watchdog: replica {rid} heartbeat stale for "
                 f"{age:.2f}s (timeout {self.watchdog.timeout:g}s) — "
                 "killing the stalled replica")
            self._kill_replica(rep, code=-int(_signal.SIGKILL),
                               stalled=True, now=now, detect_age=age)

    def _relaunch_due(self, now: float) -> None:
        for rep in self.replicas:
            if rep.state != "dead" or now < rep.relaunch_at:
                continue
            if self.restarts_used >= self.fleet.max_restarts:
                rep.state = "failed"
                _log(f"replica {rep.id}: restart budget exhausted "
                     f"({self.restarts_used}/{self.fleet.max_restarts} "
                     "used) — marking failed; the fleet degrades")
                continue
            self.restarts_used += 1
            rep.restarts += 1
            fresh = self._spawn(rep.id)
            rep.engine = fresh.engine
            rep.heartbeat = fresh.heartbeat
            rep.state = "healthy"
            rep.exit = None
            if self.watchdog is not None:
                # The PREVIOUS incarnation's kill record must not mute
                # watching the fresh one.
                self.watchdog.kills.pop(rep.id, None)
            _log(f"replica {rep.id} relaunched (attempt {rep.restarts}; "
                 f"{self.fleet.max_restarts - self.restarts_used} "
                 "restart(s) left fleet-wide)")
        if not self.alive and self.queue:
            # Zero replicas left, forever: shed the backlog instead of
            # holding clients in a queue that can never drain.
            _log(f"all replicas failed — shedding {len(self.queue)} "
                 "queued request(s)")
            for req in self.queue:
                self._reject(req, "overloaded")
            self.queue = []

    # ------------------------------------------------------- dispatch

    def _expire_queued(self, now: float) -> None:
        """Router-level TTL sweep: a request can blow its deadline
        waiting in the FLEET queue (each engine sweeps its own)."""
        expired = [r for r in self.queue if r.expired(now)]
        if not expired:
            return
        self.queue = [r for r in self.queue if not r.expired(now)]
        for req in expired:
            req.state = RequestState.TIMEOUT
            req.t_finish = now
            self.timed_out.append(req)

    def _dispatch(self) -> None:
        while self.queue:
            req = self.queue[0]
            rep = pick_replica(self.replicas, req)
            if rep is None:
                break   # head waits; order (and requeue priority) holds
            self.queue.pop(0)
            if not rep.engine.scheduler.submit(req):
                # Defensive only: eligible() mirrors every admission
                # check (geometry, in-flight headroom, the engine's own
                # bounded queue), so a failure here means drift the
                # router could not see. The engine already stamped the
                # reject and listed it — move that ONE record to the
                # fleet list (never both: stats must not double-count).
                if req in rep.engine.scheduler.rejected:
                    rep.engine.scheduler.rejected.remove(req)
                self.rejected.append(req)
                if req.reject_reason == "overloaded":
                    self.shed_total += 1
                continue
            rep.assigned.append(req)

    def _collect(self, rep: Replica) -> None:
        """Pull terminal requests out of a live replica into the fleet
        lists and release router bookkeeping."""
        eng = rep.engine
        done: List[Request] = []
        if eng.finished:
            for req in eng.finished:
                if req.t_finish is not None and req.t_admit is not None:
                    self._service_samples.append(
                        req.t_finish - req.t_admit)
            self.finished.extend(eng.finished)
            done.extend(eng.finished)
            eng.finished = []
        if eng.timed_out:
            self.timed_out.extend(eng.timed_out)
            done.extend(eng.timed_out)
            eng.timed_out = []
        if eng.evicted:
            self.evicted.extend(eng.evicted)
            done.extend(eng.evicted)
            eng.evicted = []
        if eng.scheduler.rejected:
            self.rejected.extend(eng.scheduler.rejected)
            done.extend(eng.scheduler.rejected)
            eng.scheduler.rejected = []
        if done:
            gone = set(id(r) for r in done)
            rep.assigned = [r for r in rep.assigned
                            if id(r) not in gone]

    # ------------------------------------------------------------ step

    def step(self) -> bool:
        """One fleet tick: inject due faults, run the watchdog, process
        due relaunches, expire queued deadlines, dispatch, then step
        every live replica once. Returns whether any replica made
        progress (False = idle, everything stalled, or everything
        waiting on a backoff — callers let wall time pass)."""
        if self._closed:
            raise RuntimeError("step() on a closed ServeFleet")
        now = self.clock()
        if self._fault_t0 is None:
            self._fault_t0 = now
        self._inject_faults(now)
        self._check_watchdog(now)
        self._relaunch_due(now)
        self._expire_queued(now)
        self._dispatch()

        progressed = False
        occ: List[float] = []
        ticked: List[Replica] = []
        for rep in self.replicas:
            if not rep.healthy:
                continue
            if rep.stall_until is not None:
                if now < rep.stall_until:
                    continue   # no step, no heartbeat: a silent stall
                rep.stall_until = None
            t0 = self.clock()
            try:
                stepped = rep.engine.step()
            except Exception as e:
                # A REAL replica crash (engine bug, allocator error,
                # device OOM) — the docstring's contract: one replica
                # is one failure domain. Classify + drain + relaunch
                # like any kill; never let it abort the fleet loop.
                import traceback

                _log(f"replica {rep.id} raised "
                     f"{type(e).__name__}: {e} — classifying as a "
                     "crash\n" + traceback.format_exc())
                self._kill_replica(rep, code=1, stalled=False, now=now)
                continue
            if stepped:
                progressed = True
                rep.steps += 1
                if rep.slow_factor > 1.0:
                    dt = self.clock() - t0
                    if dt > 0:
                        self._sleep((rep.slow_factor - 1.0) * dt)
            ticked.append(rep)
            self._collect(rep)
            occ.append(rep.engine.cache.occupancy())
        # Heartbeats stamp at the END of the tick, together: replicas
        # step sequentially in-process, so stamping each inside the
        # loop would let one slow step (a fresh replica's compile) age
        # every PEER's file past the watchdog timeout — a spurious
        # "stalled" kill of a healthy replica. End-of-tick stamping
        # means the next check (top of the following tick) sees ~zero
        # age for every replica that completed this tick; only
        # genuinely skipped replicas — stalled or dead — go stale. An
        # idle-but-healthy replica still stamps (engine.step() False is
        # "nothing to do", not "wedged").
        for rep in ticked:
            rep.heartbeat.touch(rep.steps)
        if occ:
            self.occupancy_samples.append(sum(occ) / len(occ))
        self.steps += 1
        return progressed

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drain to idle (or ``max_steps`` fleet ticks); returns
        requests finished so far. Ticks that make no progress (a stall
        waiting for the watchdog, a relaunch waiting out its backoff)
        sleep briefly so wall time — which heartbeat mtimes and
        backoffs are measured in — actually passes."""
        while not self.idle:
            if max_steps is not None and self.steps >= max_steps:
                break
            if not self.step():
                if self.idle:
                    break
                self._sleep(0.001)
        return self.finished

    # ---------------------------------------------------------- stats

    def reset_metrics(self) -> None:
        """Bench warmup discipline (compile+warm every replica, then
        measure from a clean slate). Only valid when idle; replica
        health/restart state survives (a mid-life reset must not
        forget a failed replica)."""
        if not self.idle:
            raise RuntimeError("reset_metrics with requests in flight")
        self.finished = []
        self.timed_out = []
        self.evicted = []
        self.rejected = []
        self._service_samples.clear()
        self.incidents = []
        self.incidents_by_class = {}
        self.redispatched_total = 0
        self.tokens_recomputed_total = 0
        self.shed_total = 0
        self.occupancy_samples = []
        self.steps = 0
        for rep in self.replicas:
            if rep.healthy and rep.engine is not None:
                rep.engine.reset_metrics()
                rep.steps = 0
        self._fault_t0 = None
        self._t_start = self.clock()

    def stats(self) -> Dict:
        """SLO metrics over every request seen, plus the ``fleet``
        block: per-replica occupancy/health, rejection/timeout/
        redispatch counts, classified incidents, and
        detection/recovery evidence (the router-level satellite of
        ROADMAP's "serve-engine TTL/SLO metrics in the fleet
        router")."""
        from horovod_tpu.serve.metrics import summarize

        in_service = [r for rep in self.replicas for r in rep.assigned]
        everything = (self.finished + self.timed_out + self.evicted
                      + self.rejected + list(self.queue) + in_service)
        out = summarize(everything, self.clock() - self._t_start,
                        self.chips, self.occupancy_samples)
        by_reason: Dict[str, int] = {}
        for req in self.rejected:
            key = req.reject_reason or "?"
            by_reason[key] = by_reason.get(key, 0) + 1
        detect = [i["detect_s"] for i in self.incidents
                  if i["category"] == "stalled"]
        out["fleet"] = {
            "replicas": len(self.replicas),
            "healthy": sum(1 for r in self.replicas if r.healthy),
            "dead": sum(1 for r in self.replicas if r.state == "dead"),
            "failed": sum(1 for r in self.replicas
                          if r.state == "failed"),
            "queued": len(self.queue),
            "redispatched": self.redispatched_total,
            "tokens_recomputed": self.tokens_recomputed_total,
            "shed": self.shed_total,
            "rejected_by_reason": by_reason,
            "timeout": len(self.timed_out),
            "incidents": list(self.incidents),
            "incidents_by_class": dict(self.incidents_by_class),
            "restarts_used": self.restarts_used,
            "max_restarts": self.fleet.max_restarts,
            "detect_s": round(max(detect), 4) if detect else None,
            "per_replica": [
                dict(replica_load(r), id=r.id, state=r.state,
                     steps=r.steps, restarts=r.restarts)
                for r in self.replicas],
        }
        return out
